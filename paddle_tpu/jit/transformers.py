"""Dy2static AST transformers.

Parity: python/paddle/jit/dy2static/transformers/ (reference — the 18 AST
transformers driven by program_translator.py:776; ifelse_transformer.py,
loop_transformer.py, logical_transformer.py, call_transformer.py).

TPU-native design: the rewritten constructs target the jax structured
control-flow primitives through runtime converters (convert_ops.py) — a
tensor-predicate ``if`` becomes ``lax.cond``, a tensor ``while`` becomes
``lax.while_loop`` — so data-dependent control flow lives INSIDE the
compiled XLA module instead of breaking the trace.  Python-value
predicates keep exact python semantics (the converters dispatch at run
time, like the reference's convert_* operators).

Supported subset (documented, mirrors the reference's practical coverage):
- ``if``/``elif``/``else`` with tensor predicates, where branches assign
  variables;
- ``break``/``continue`` in ``for``/``while`` and mid-function
  ``return``: a flattening pre-pass (_FlattenEarlyExits — the analog of
  the reference's break_continue_transformer.py + return_transformer.py)
  rewrites them into flag variables + guarded tails, after which the
  structural converters apply as usual (the flags simply ride the loop
  carry);
- ``while`` with tensor predicates; NOTE: a traced-tensor ``while``
  compiles to ``lax.while_loop``, which XLA cannot
  reverse-differentiate — use it in inference/metrics paths, or a
  python-bounded ``for`` (stays unrolled, fully differentiable) in
  training code;
- ``for i in range(...)``: python bounds stay a plain unrolled python
  loop (differentiable); traced-tensor bounds lower to a while loop
  (forward-only, same XLA constraint);
- ``and`` / ``or`` / ``not`` over tensor operands (short-circuiting
  preserved for python values);
- recursive conversion of called user functions (convert_call).
Constructs outside the subset are left as plain python: they still work
whenever their predicates are python values, exactly like before.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import List, Optional, Set

_COUNTER = [0]


def _fresh(prefix: str) -> str:
    _COUNTER[0] += 1
    return f"__pt_{prefix}_{_COUNTER[0]}"


# ---------------------------------------------------------------------------
# name analysis
# ---------------------------------------------------------------------------
class _Names(ast.NodeVisitor):
    def __init__(self):
        self.stored: Set[str] = set()
        self.loaded: Set[str] = set()
        self.funcs: Set[str] = set()   # nested defs: not data-flow values

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.stored.add(node.id)
        else:
            self.loaded.add(node.id)

    def visit_FunctionDef(self, node):   # don't descend into nested defs
        self.funcs.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _analyze(stmts) -> _Names:
    v = _Names()
    for s in stmts:
        v.visit(s)
    return v


def _contains(stmts, kinds) -> bool:
    class F(ast.NodeVisitor):
        found = False

        def generic_visit(self, node):
            if isinstance(node, kinds):
                self.found = True
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                super().generic_visit(node)
    f = F()
    for s in stmts:
        f.visit(s)
    return f.found


def _try_read_default(name: str) -> ast.expr:
    """``_jst.try_read(lambda: name)`` — evaluated at def time, yields the
    current outer binding or the UNDEF sentinel."""
    return ast.Call(
        func=ast.Attribute(ast.Name("_jst", ast.Load()), "try_read",
                           ast.Load()),
        args=[ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=ast.Name(name, ast.Load()))],
        keywords=[])


def _names_tuple(names: List[str], ctx) -> ast.expr:
    return ast.Tuple([ast.Name(n, ctx()) for n in names], ctx())


# ---------------------------------------------------------------------------
# early-exit flattening (break / continue / mid-function return)
# ---------------------------------------------------------------------------
def _assign(name: str, value: ast.expr) -> ast.stmt:
    return ast.Assign(targets=[ast.Name(name, ast.Store())], value=value)


def _not_flags(flags: List[str]) -> ast.expr:
    """``not (f1 or f2 or ...)`` guard expression."""
    if len(flags) == 1:
        test = ast.Name(flags[0], ast.Load())
    else:
        test = ast.BoolOp(op=ast.Or(),
                          values=[ast.Name(f, ast.Load())
                                  for f in flags])
    return ast.UnaryOp(op=ast.Not(), operand=test)


class _FlattenEarlyExits(ast.NodeTransformer):
    """Rewrite ``break``/``continue``/mid-function ``return`` into flag
    variables and guarded statement tails, so the structural converters
    (if -> cond, while/for -> loop) apply afterwards.

    Parity: the reference's dedicated transformers
    (python/paddle/jit/dy2static/transformers/break_continue_transformer
    .py, return_transformer.py, early_return_transformer.py) — same
    flag-plus-guard strategy, one pass here because the flags compose:
    ``return`` inside a loop lowers to ret-flag + ``break``, which the
    loop pass then lowers to the loop's break flag."""

    # ---- function level: returns --------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef):
        if not self._has_early_return(node.body):
            node.body = self._flatten_loops_block(node.body)
            return node
        rf, rv = _fresh("ret_flag"), _fresh("ret_val")
        body = self._rewrite_returns_block(node.body, rf, rv,
                                           in_loop=False)
        body = self._flatten_loops_block(body)
        node.body = ([_assign(rf, ast.Constant(False)),
                      _assign(rv, ast.Constant(None))] + body
                     + [ast.Return(ast.Name(rv, ast.Load()))])
        return node

    @staticmethod
    def _has_early_return(stmts) -> bool:
        # any Return not a top-level tail statement
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Return) and i == len(stmts) - 1:
                continue
            if _contains([s], (ast.Return,)):
                return True
        return False

    def _rewrite_returns_block(self, stmts, rf, rv, in_loop):
        """Replace every Return with rf/rv assignment (+ break inside a
        loop); guard statements after any construct that may have
        returned."""
        out = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Return):
                out.append(_assign(rv, s.value
                                   if s.value is not None
                                   else ast.Constant(None)))
                out.append(_assign(rf, ast.Constant(True)))
                if in_loop:
                    out.append(ast.Break())
                return out          # following statements unreachable
            if isinstance(s, ast.If) and _contains([s], (ast.Return,)):
                s = ast.If(
                    test=s.test,
                    body=self._rewrite_returns_block(s.body, rf, rv,
                                                     in_loop),
                    orelse=self._rewrite_returns_block(s.orelse, rf, rv,
                                                       in_loop)
                    if s.orelse else [])
                out.append(s)
                rest = self._rewrite_returns_block(stmts[i + 1:], rf,
                                                   rv, in_loop)
                if rest:
                    out.append(ast.If(test=_not_flags([rf]), body=rest,
                                      orelse=[]))
                return out
            if isinstance(s, (ast.For, ast.While)) \
                    and _contains([s], (ast.Return,)):
                s = type(s)(**{**{f: getattr(s, f)
                                  for f in s._fields},
                               "body": self._rewrite_returns_block(
                                   s.body, rf, rv, in_loop=True)})
                out.append(s)
                if in_loop:
                    # a return inside a NESTED loop must break every
                    # enclosing loop, not just the innermost one
                    out.append(ast.If(test=ast.Name(rf, ast.Load()),
                                      body=[ast.Break()], orelse=[]))
                rest = self._rewrite_returns_block(stmts[i + 1:], rf,
                                                   rv, in_loop)
                if rest:
                    out.append(ast.If(test=_not_flags([rf]), body=rest,
                                      orelse=[]))
                return out
            out.append(s)
        return out

    # ---- loop level: break / continue ---------------------------------
    def _flatten_loops_block(self, stmts):
        out = []
        for s in stmts:
            if isinstance(s, (ast.For, ast.While)):
                res = self._flatten_loop(s)
                out.extend(res if isinstance(res, list) else [res])
            elif isinstance(s, ast.If):
                out.append(ast.If(
                    test=s.test,
                    body=self._flatten_loops_block(s.body),
                    orelse=self._flatten_loops_block(s.orelse)
                    if s.orelse else []))
            else:
                out.append(s)
        return out

    def _flatten_loop(self, node):
        # flatten nested loops inside this body first
        node.body = self._flatten_loops_block(node.body)
        has_break = self._direct_exit(node.body, ast.Break)
        has_cont = self._direct_exit(node.body, ast.Continue)
        if not has_break and not has_cont:
            return node
        bf = _fresh("break_flag") if has_break else None
        cf = _fresh("cont_flag") if has_cont else None
        body = self._rewrite_exits_block(node.body, bf, cf)
        if cf:
            body = [_assign(cf, ast.Constant(False))] + body
        # for/while ... else: runs iff the loop exited WITHOUT break —
        # flatten to a guarded tail (the structural converters reject
        # orelse, so it must not survive on the loop node itself)
        if node.orelse and bf:
            post = [ast.If(test=_not_flags([bf]), body=list(node.orelse),
                           orelse=[])]
        else:
            post = list(node.orelse) if node.orelse else []
        if isinstance(node, ast.While):
            test = node.test
            if bf:
                # the flag must short-circuit FIRST: after a break the
                # original condition may no longer be evaluable (python
                # never re-tests it after break)
                test = ast.BoolOp(op=ast.And(),
                                  values=[_not_flags([bf]), test])
            new = ast.While(test=test, body=body, orelse=[])
        else:
            # for loop with break: guard the whole body per iteration
            # (the iterator still advances, matching a flagged python
            # loop over the same iterable)
            if bf:
                body = [ast.If(test=_not_flags([bf]), body=body,
                               orelse=[])]
            new = ast.For(target=node.target, iter=node.iter, body=body,
                          orelse=[])
        pre = [_assign(bf, ast.Constant(False))] if bf else []
        if pre or post:
            return pre + [new] + post
        return new

    @staticmethod
    def _direct_exit(stmts, kind) -> bool:
        """kind occurs in stmts WITHOUT an intervening loop (i.e. it
        belongs to this loop, not a nested one)."""
        class F(ast.NodeVisitor):
            found = False

            def generic_visit(self, n):
                if isinstance(n, kind):
                    self.found = True
                if not isinstance(n, (ast.For, ast.While,
                                      ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    super().generic_visit(n)
        f = F()
        for s in stmts:
            f.visit(s)
        return f.found

    def _rewrite_exits_block(self, stmts, bf, cf):
        out = []
        flags = [f for f in (bf, cf) if f]
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(_assign(bf, ast.Constant(True)))
                return out
            if isinstance(s, ast.Continue):
                out.append(_assign(cf, ast.Constant(True)))
                return out
            if isinstance(s, ast.If) and self._direct_exit(
                    [s], (ast.Break, ast.Continue)):
                s = ast.If(test=s.test,
                           body=self._rewrite_exits_block(s.body, bf,
                                                          cf),
                           orelse=self._rewrite_exits_block(
                               s.orelse, bf, cf) if s.orelse else [])
                out.append(s)
                rest = self._rewrite_exits_block(stmts[i + 1:], bf, cf)
                if rest:
                    out.append(ast.If(test=_not_flags(flags), body=rest,
                                      orelse=[]))
                return out
            out.append(s)
        return out


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

def _fndef(name, args, body):
    fd = ast.FunctionDef(name=name, args=args, body=body,
                         decorator_list=[])
    fd.type_params = []   # required field on py3.12 ASTs
    return fd

class Dy2StaticTransformer(ast.NodeTransformer):
    # -- logical ops --------------------------------------------------------
    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        fname = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        out = node.values[-1]
        for val in reversed(node.values[:-1]):
            out = ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()), fname,
                                   ast.Load()),
                args=[ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=val),
                    ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=out)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_logical_not", ast.Load()),
                args=[node.operand], keywords=[])
        return node

    # -- if/else ------------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        branches = node.body + node.orelse
        if _contains(branches, (ast.Return, ast.Break, ast.Continue,
                                ast.Yield, ast.YieldFrom)):
            return node   # unsupported in a branch fn: keep python

    # assigned names (either branch) become the branch-fn outputs
        t = _analyze(node.body)
        f = _analyze(node.orelse)
        assigned = sorted((t.stored | f.stored) - t.funcs - f.funcs
                          - {"_", "_jst"})
        if not assigned:
            return node   # side-effect-only branches: keep python

        tname, fname = _fresh("true_fn"), _fresh("false_fn")
        args = ast.arguments(
            posonlyargs=[], kwonlyargs=[], kw_defaults=[],
            args=[ast.arg(n) for n in assigned],
            defaults=[_try_read_default(n) for n in assigned])
        ret = ast.Return(_names_tuple(assigned, ast.Load))
        true_def = _fndef(tname, args, node.body + [ret])
        false_def = _fndef(fname, args,
                           (node.orelse or [ast.Pass()]) + [ret])
        call = ast.Assign(
            targets=[_names_tuple(assigned, ast.Store)],
            value=ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_ifelse", ast.Load()),
                args=[node.test, ast.Name(tname, ast.Load()),
                      ast.Name(fname, ast.Load())],
                keywords=[]))
        return [true_def, false_def, call]

    # -- while --------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse or _contains(
                node.body, (ast.Break, ast.Continue, ast.Return,
                            ast.Yield, ast.YieldFrom)):
            return node

        body_names = _analyze(node.body)
        # anything the body stores may be read by the condition or after
        # the loop (unknowable locally) — carry all stored names
        loop_vars = sorted(body_names.stored - body_names.funcs
                           - {"_", "_jst"})
        if not loop_vars:
            return node

        cname, bname = _fresh("while_cond"), _fresh("while_body")
        args = ast.arguments(posonlyargs=[], kwonlyargs=[],
                             kw_defaults=[], defaults=[],
                             args=[ast.arg(n) for n in loop_vars])
        cond_def = _fndef(cname, args, [ast.Return(node.test)])
        body_def = _fndef(
            bname, args,
            node.body + [ast.Return(_names_tuple(loop_vars, ast.Load))])
        call = ast.Assign(
            targets=[_names_tuple(loop_vars, ast.Store)],
            value=ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_while_loop", ast.Load()),
                args=[ast.Name(cname, ast.Load()),
                      ast.Name(bname, ast.Load()),
                      ast.Tuple([_try_read_default(n)
                                 for n in loop_vars], ast.Load())],
                keywords=[]))
        return [cond_def, body_def, call]

    # -- for i in range(...) ------------------------------------------------
    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        if (node.orelse
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or _contains(node.body, (ast.Break, ast.Continue,
                                         ast.Return, ast.Yield,
                                         ast.YieldFrom))):
            return node

        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(0), rargs[0], ast.Constant(1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(1)
        else:
            start, stop, step = rargs

        ivar = node.target.id
        body_names = _analyze(node.body)
        loop_vars = sorted(body_names.stored - body_names.funcs
                           - {ivar, "_", "_jst"})

        bname = _fresh("for_body")
        args = ast.arguments(
            posonlyargs=[], kwonlyargs=[], kw_defaults=[], defaults=[],
            args=[ast.arg(ivar)] + [ast.arg(n) for n in loop_vars])
        body_def = _fndef(
            bname, args,
            node.body + [ast.Return(_names_tuple(loop_vars, ast.Load))])
        # the index stays bound after the loop (python semantics)
        targets = _names_tuple([ivar] + loop_vars, ast.Store)
        call = ast.Assign(
            targets=[targets],
            value=ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_for_range", ast.Load()),
                args=[start, stop, step, ast.Name(bname, ast.Load()),
                      ast.Tuple([_try_read_default(n)
                                 for n in loop_vars], ast.Load())],
                keywords=[]))
        return [body_def, call]

    # -- nested calls -------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        # only wrap plain-name calls: attribute calls are overwhelmingly
        # framework/methods, and wrapping them would be pure overhead
        if isinstance(node.func, ast.Name) and node.func.id not in (
                "range", "len", "print", "isinstance", "super", "_jst"):
            node.func = ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_call", ast.Load()),
                args=[node.func], keywords=[])
        return node


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def convert_function(fn):
    """AST-convert a python function for tracing; returns the original on
    any failure (no-source builtins, exotic constructs)."""
    from . import convert_ops as _jst_mod

    if isinstance(fn, functools.partial):
        inner = convert_function(fn.func)
        return functools.partial(inner, *fn.args, **(fn.keywords or {}))

    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn

    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []   # strip @to_static etc.

    tree = _FlattenEarlyExits().visit(tree)
    new_tree = Dy2StaticTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)

    glb = dict(fn.__globals__)
    glb["_jst"] = _jst_mod
    # rebind closure freevars as globals (values snapshotted now)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass

    code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, glb, ns)
    out = ns[fdef.name]
    out = functools.wraps(fn)(out)
    out.__pt_converted__ = True
    return out


def convert_to_static(call):
    """Entry used by StaticFunction: convert a function or bound method."""
    if isinstance(call, types.MethodType):
        conv = convert_function(call.__func__)
        if conv is call.__func__:
            return call
        return types.MethodType(conv, call.__self__)
    conv = convert_function(call)
    return conv
