"""Fully-fused compiled serving steps (decode + prefill scatter).

The serving analog of ``TrainStep``: one engine decode step — every
transformer layer (projections, fused RoPE, paged KV-cache append,
paged attention, MLP), the final norm, the LM head, and greedy sampling
— traced into ONE XLA module at a fixed slot count, with the per-layer
KV-cache pages passed as donated arguments so the append is an in-place
HBM update.  Parity intent: the reference's ``AnalysisPredictor::
ZeroCopyRun`` single-graph serving execution (analysis_predictor.h:210)
driven per token by the block_multihead_attention kernel.

Shape policy: the batch dimension is the engine's slot count, NEVER the
number of active requests.  Inactive slots are masked, not dropped —
their token id is 0, their seq_len is 0, and their block-table row
points every entry at the cache's sink page (PagedKVCache
``sink_block``), so their writes land in a page no request owns and
their sampled token is ignored by the host.  Admission, eviction and
slot churn therefore never change a traced shape: the decode step
compiles exactly once per engine lifetime (``compile_count`` asserts
this in tests).

The only per-step host traffic is the [slots] int32 next-token fetch —
sampling runs on device, so the 1-token logits tensor never crosses the
link.

Tensor parallelism (multi-chip serving): every step accepts
``mesh + ShardingConfig(axis='tp')`` (or a prebuilt
:class:`~.spmd.TPContext`, which the engine shares across its steps so
parameters are placed once).  The SAME traced body then runs as an
explicit SPMD program (``shard_map`` over the tp axis): weights shard
by the canonical per-family specs in ``jit/spmd.py`` (vocab-row
embeddings, head-column QKV, head-row attention-out, ffn-column
gate/up, ffn-row down, vocab-column LM head), the paged KV pools shard
over kv heads (each chip's paged-attention launch sees only its head
shard of every page), and activations cross chip boundaries through
exactly one psum per layer boundary (attention out, MLP out) plus one
exact embedding psum and one exact logits all-gather.  Donation, the
compile-count invariants, and the single packed int32 host transfer
all survive sharding unchanged.

Quantization (round 13): when the engine's pools are int8
(``PagedKVCache(kv_dtype="int8")``) the same traced bodies switch to
the quantize-on-write/dequant-on-read ops and thread the per-layer
scale tables through as extra donated operands (EMPTY tuples on the fp
path, so the default trace — and compiled module — stays
byte-identical); a serving-PTQ weight tree (int8 + ``::scale``
vectors) replaces the fp params operand and ``_materialize_params``
dequantizes it inside the trace; ``quant_collectives`` swaps the exact
tp logits all-gather for the EQuARX-style int8 one.  All
tolerance-gated by ``tools/bench_serving.py --quant``
(BENCH_QUANT_r13.json).

Sampling + speculative decoding (round 14): ``sampling=True`` swaps
the greedy argmax for the ``ops/sampling`` epilogue — per-request
temperature / top-k / top-p with a per-slot seeded counter-based PRNG
(``fold_in`` on the request seed + the sampled token's global
position).  Every knob and seed is traced DATA: the split steps take
one extra ``[..., 4]`` int32 operand (fp knobs BITCAST into the int32
lane), the mixed step grows its packed buffer's span rows by four
columns — so changing a temperature or a seed never retraces, and
``temperature=0`` rows take the exact greedy argmax.  Under tp the
epilogue runs AFTER the exact logits all-gather on replicated data, so
tp sampling is byte-identical to single-chip.  ``spec_k=K`` puts the
speculative VERIFY epilogue into the mixed step: spans may carry up to
K draft tokens (an ``n_draft`` pack column), the LM head sees each
span's K+2 gathered rows instead of 1, and the standard accept/reject
+ rejection-resampling scan (``ops/sampling.spec_verify``) emits
``(token, n_acc)`` per span.  ``return_probs=True`` (the draft
model's role) additionally returns each span's filtered proposal
distribution, device-resident, for the verifier's residual.  All off
by default — a default-config step's operand pytree and traced body
are byte-identical to round 13.

Kernel performance pass (round 17): every traced body routes the
per-layer pre-attention transforms through the fused RoPE+QKV
epilogue (``ops/pallas_kernels.rope_qkv_epilogue`` — rope(q), rope(k)
and, on int8 pools, the per-token K/V absmax rows in ONE pass over
the projection outputs; one Pallas kernel on TPU, a bit-identical XLA
reference on CPU), with the cos/sin tables built once per step
(``rope_tables_for_positions``) instead of once per layer.  The
quantized writes consume the epilogue's absmax rows instead of
re-reading k/v.  fp32 outputs are byte-identical to the round-16
wiring; the attention kernels underneath gained double-buffered page
DMA and the int8 MXU path (see BASELINE.md "round 17").
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..core.tensor import Tensor
from .spmd import (TPContext, tp_embed, tp_gather_logits,
                   tp_gather_logits_q8, tp_serving_context)

__all__ = ["DecodeStep", "PrefillStep", "MixedStep", "prefill_scatter",
           "copy_block", "extract_blocks", "inject_blocks",
           "migration_compiles", "migration_transfers"]


def _resolve_tp(model, mesh, sharding, tp: Optional[TPContext]
                ) -> Optional[TPContext]:
    """Step-constructor tp plumbing: a prebuilt shared context wins;
    otherwise resolve mesh+config here (standalone step construction).
    None = single-chip."""
    if tp is not None:
        return tp
    if mesh is None and sharding is None:
        return None
    return tp_serving_context(model, mesh, sharding)


def _inner_model(model):
    """The decoder stack behind a CausalLM wrapper — ``model.llama``
    (dense) or ``model.mixtral`` (MoE, round 24).  Both expose the same
    ``embed_tokens / layers / norm`` surface, which is everything the
    traced bodies touch; per-layer FFN dispatch branches on the LAYER
    (``block_sparse_moe`` vs ``mlp``), not the wrapper."""
    inner = getattr(model, "llama", None)
    if inner is None:
        inner = getattr(model, "mixtral", None)
    if inner is None:
        raise ValueError(
            "serving steps need a LlamaForCausalLM-shaped model (an "
            "inner .llama or .mixtral decoder stack); got %r"
            % type(model).__name__)
    return inner


def _embed(llama, tokens, tp: Optional[TPContext]) -> Tensor:
    """Embedding lookup shared by all three traced bodies: the module's
    gather single-chip (and pure-fsdp, whose params are full after the
    prologue gather), the vocab-parallel masked lookup + exact psum
    under tp.  ``tokens`` already carries the body's batch shape."""
    if tp is None or tp.axis is None:
        return llama.embed_tokens(Tensor._from_value(tokens))
    return Tensor._from_value(tp_embed(
        llama.embed_tokens.weight._value, tokens, tp.axis))


def _tp_psum(t: Tensor, tp: Optional[TPContext]) -> Tensor:
    """The layer-boundary collective: identity single-chip, psum of the
    row-sharded projection's partial sums over the tp axis otherwise.
    (The ONE place the per-layer collective lives — the spot a
    quantized all-reduce would drop into.)"""
    if tp is None or tp.axis is None:
        return t
    return Tensor._from_value(jax.lax.psum(t._value, tp.axis))


def _moe_ffn(blk, h2: Tensor, tp: Optional[TPContext]) -> Tensor:
    """The fused dropless MoE FFN (round 24), traced into the step body
    in place of ``layer.mlp``: shared top-k gate over the block's
    tokens, GShard dense dispatch into per-expert buffers sized so no
    assignment ever drops, grouped expert SwiGLU, weighted combine.
    Under an ``ep`` mesh axis the dispatch/combine pair crosses the
    axis as two ``all_to_all`` exchanges plus one token-stripe
    ``all_gather`` (see ``ops.moe_gate.moe_ffn``).

    No ``_tp_psum`` boundary here: the combine output is the FULL
    activation (each assignment contributes exactly one expert's
    output), already replicated across tp — the expert banks never
    shard over tp."""
    from ..ops.moe_gate import moe_ffn
    ep_axis = tp.ep_axis if tp is not None else None
    ep_deg = tp.ep_degree if tp is not None else 1
    v = h2._value
    flat = v.reshape(-1, v.shape[-1])
    out = moe_ffn(flat, blk.gate.weight._value, blk.w_gate._value,
                  blk.w_up._value, blk.w_down._value, top_k=blk.top_k,
                  ep_axis=ep_axis, ep_degree=ep_deg)
    return Tensor._from_value(out.reshape(v.shape))


def _ffn(layer, h2: Tensor, tp: Optional[TPContext]) -> Tensor:
    """Per-layer FFN dispatch shared by all three traced bodies: the
    Megatron-sharded dense MLP (+ its psum boundary) for llama layers,
    the fused MoE path for Mixtral layers."""
    if hasattr(layer, "block_sparse_moe"):
        return _moe_ffn(layer.block_sparse_moe, h2, tp)
    return _tp_psum(layer.mlp(h2), tp)


def _tp_logits(logits: Tensor, tp: Optional[TPContext],
               q8: bool = False) -> Tensor:
    """Identity single-chip; the vocab-shard all-gather under tp, so
    the on-device argmax sees the full vocab row.  ``q8`` swaps in the
    EQuARX-style int8 gather (``spmd.tp_gather_logits_q8``) — ~4× less
    interconnect payload, tolerance-gated instead of exact."""
    if tp is None or tp.axis is None:
        return logits
    if q8:
        return Tensor._from_value(
            tp_gather_logits_q8(logits._value, tp.axis))
    return Tensor._from_value(tp_gather_logits(logits._value, tp.axis))


def _cp_local_dest(dest_blocks, dest_offsets, bsl, cp_axis, sink):
    """Translate GLOBAL per-token write destinations into this chip's
    slot stripe (round 22, traced inside the shard_map body).

    Under cp the pool's block_size dim is striped: chip ``r`` holds
    slots ``[r*bsl, (r+1)*bsl)`` of every page, where ``bsl`` is the
    LOCAL shard's slot count (``block_size/cp``).  A token whose global
    in-page offset falls in this chip's stripe writes at the local
    offset; every other chip routes that token to its OWN sink-page
    stripe (the same garbage-absorbing page padding already uses), so
    one scatter per chip writes each K/V row exactly once pool-wide.
    """
    r = jax.lax.axis_index(cp_axis)
    lo = dest_offsets - r * bsl
    owned = (lo >= 0) & (lo < bsl)
    n = dest_offsets.shape[0]
    blk = jnp.where(owned, dest_blocks, jnp.int32(sink))
    off = jnp.where(owned, lo, jnp.arange(n, dtype=jnp.int32) % bsl)
    return blk, off


def _samp_knobs(samp):
    """Decode a packed per-row sampling operand ``[..., 4]`` int32 into
    ``(temps f32, top_ks i32, top_ps f32, seeds i32)``.  Temperature
    and top-p ride BITCAST in the int32 lane (the same trick the quant
    scales use on the scalar-prefetch path), so one dtype-uniform
    buffer carries every knob and the packed host transfer stays a
    single int32 array."""
    t = jax.lax.bitcast_convert_type(samp[..., 0], jnp.float32)
    p = jax.lax.bitcast_convert_type(samp[..., 2], jnp.float32)
    return t, samp[..., 1], p, samp[..., 3]


def _materialize_params(params, dtype):
    """Dequant-on-use prologue shared by every traced step body: a
    serving-PTQ tree (int8 weights + ``::scale`` vectors) comes back as
    the fp dict ``bind_state`` expects, with the dequant traced INTO
    the step so XLA fuses it into the consuming matmuls and HBM keeps
    only the int8 tree.  A plain fp tree passes through untouched (the
    default path's trace is unchanged)."""
    from ..quantization.functional import (dequantize_param_tree,
                                           is_weight_scale_key)
    if not any(is_weight_scale_key(k) for k in params):
        return params
    return dequantize_param_tree(params, dtype)


def _step_params(param_tensors, tp: Optional[TPContext], qtree=None):
    """The params operand for one step call: plain values single-chip;
    under tp the context's ONE placed (sharded) copy — so the jit's
    in_shardings alias instead of resharding, and placement happens
    once per engine, not per step or per call.  ``qtree`` (the
    serving-PTQ int8+scales tree) replaces the live model values when
    weight quantization is on — it is device-resident and immutable,
    so steady state is pointer-identical."""
    vals = qtree if qtree is not None \
        else {k: t._value for k, t in param_tensors.items()}
    if tp is None:
        return vals
    return tp.place_params(vals)


def _cache_scales(caches, quant_kv: bool):
    """The per-layer scale-table operands: empty tuples for fp pools,
    so the default path's pytree — and therefore its compiled module —
    is byte-identical to the pre-quantization steps."""
    if not quant_kv:
        return (), ()
    return (tuple(c.key_scale for c in caches),
            tuple(c.value_scale for c in caches))


def _rebind_caches(caches, new_kcs, new_vcs, new_kss, new_vss):
    """Rebind the donated pool (and scale, when quantized) arrays onto
    their PagedKVCache owners after a step."""
    for i, (c, kc, vc) in enumerate(zip(caches, new_kcs, new_vcs)):
        c.key_cache = kc
        c.value_cache = vc
        if new_kss:
            c.key_scale = new_kss[i]
            c.value_scale = new_vss[i]


def _ensure_quant_specs(tp: Optional[TPContext], qtree) -> None:
    """Register the PTQ tree's ``::scale`` keys in the shared context's
    spec table (idempotent — the engine's steps share one TPContext)
    and reject an incompatible layout up front: a column-sharded
    weight's scale vector must itself split by tp."""
    if tp is None or qtree is None:
        return
    from .spmd import llama_param_specs, mixtral_param_specs
    missing = [k for k in qtree if k not in tp.specs]
    if missing:
        specs_fn = mixtral_param_specs if any(
            "block_sparse_moe" in k for k in qtree) else llama_param_specs
        tp.specs.update(specs_fn(
            missing, tp.layout,
            shapes={k: tuple(qtree[k].shape) for k in missing},
            mesh=tp.mesh))
    for k, v in qtree.items():
        spec = tp.specs[k]
        if v.ndim == 1 and tuple(spec) and spec[0] is not None \
                and v.shape[0] % tp.degree:
            raise ValueError(
                "quantized weights are incompatible with this tp spec: "
                "scale vector %r has %d channels, not divisible by the "
                "tp degree %d (spec %s)"
                % (k, v.shape[0], tp.degree, spec))


def _wrap_sharded(step, tp: TPContext, params_dict, n_layers: int,
                  n_repl: int, donate, quant_kv: bool = False):
    """Wrap a serving-step body as the explicit SPMD program: shard_map
    over the mesh (params by family spec — including int8 weights
    and their scale vectors, the ``n_repl`` host operands replicated,
    per-layer KV pools head-sharded with their absmax tables when
    quantized) under a jit whose in/out shardings pin the placed
    layouts — donation of the pools carries through, so the cache
    append stays an in-place HBM update on every chip.

    2D mesh (round 21): when the context carries an fsdp axis, the
    params enter in their fsdp×tp STORAGE placement (the same one the
    2D train step produces — zero re-sharding) and a prologue
    all-gathers each fsdp-sharded param back to its tp compute shard
    before the unchanged body runs; pools and host operands never name
    fsdp, so they replicate across it (and across any extra replica
    axis) for free."""
    from ..core.jax_compat import shard_map_compat
    from .spmd import fsdp_gather
    repl = PartitionSpec()
    pspecs = {k: tp.specs[k] for k in params_dict}
    pools = (tp.layout.kv_pool(),) * n_layers
    spools = (tp.layout.kv_scale(),) * n_layers if quant_kv else ()
    in_specs = (pspecs,) + (repl,) * n_repl + (pools, pools,
                                               spools, spools)
    out_specs = (repl, pools, pools, spools, spools)
    if tp.fsdp_axis is not None:
        inner, faxis = step, tp.fsdp_axis

        def step(params, *rest):                       # noqa: F811
            params = {k: fsdp_gather(v, pspecs[k], faxis)
                      for k, v in params.items()}
            return inner(params, *rest)
    fn = shard_map_compat(step, tp.mesh, in_specs=in_specs,
                          out_specs=out_specs)
    return jax.jit(fn, donate_argnums=donate,
                   in_shardings=tp.named(in_specs),
                   out_shardings=tp.named(out_specs))


def _prefill_scatter_impl(ks, vs, kcs, vcs, block_tables, start):
    """Scatter one request's per-layer prompt K/V ([1, L, Hkv, D] each)
    into the per-layer page pools in a single traced module."""
    from ..ops.paged_attention import write_prefill_kv
    new_k, new_v = [], []
    for k, v, kc, vc in zip(ks, vs, kcs, vcs):
        kc, vc = write_prefill_kv(k, v, kc, vc, block_tables, start)
        new_k.append(kc)
        new_v.append(vc)
    return tuple(new_k), tuple(new_v)


# donate the cache pools: prefill admission is an in-place HBM write.
# One XLA dispatch per REQUEST (all layers fused), not one per layer —
# recompiles only per distinct prompt length (the scatter is tiny).
_prefill_scatter_j = jax.jit(_prefill_scatter_impl, donate_argnums=(2, 3))


def prefill_scatter(caches, kv, block_table_row):
    """Write a freshly-prefilled request's K/V into the paged caches.

    caches: per-layer PagedKVCache list (rebound in place).
    kv: per-layer (k, v) Tensors/arrays [1, L, Hkv, D] from the model's
    dense prefill forward.  block_table_row: [1, W] int32.
    """
    if getattr(caches[0], "quantized", False):
        raise NotImplementedError(
            "prefill_scatter is the legacy dense-prefill write and does "
            "not quantize; int8 KV pools prefill through the compiled "
            "PrefillStep/MixedStep paths (the engine rejects the combo "
            "at construction)")
    ks = tuple(k._value if isinstance(k, Tensor) else jnp.asarray(k)
               for k, _ in kv)
    vs = tuple(v._value if isinstance(v, Tensor) else jnp.asarray(v)
               for _, v in kv)
    kcs = tuple(c.key_cache for c in caches)
    vcs = tuple(c.value_cache for c in caches)
    bt = jnp.asarray(np.asarray(block_table_row), jnp.int32)
    start = jnp.zeros((1,), jnp.int32)
    new_k, new_v = _prefill_scatter_j(ks, vs, kcs, vcs, bt, start)
    for c, kc, vc in zip(caches, new_k, new_v):
        c.key_cache = kc
        c.value_cache = vc


def _copy_block_impl(kcs, vcs, src, dst):
    return (tuple(kc.at[dst].set(kc[src]) for kc in kcs),
            tuple(vc.at[dst].set(vc[src]) for vc in vcs))


def _copy_block_q8_impl(kcs, vcs, kss, vss, src, dst):
    """Quantized pools: the page's int8 codes AND its per-head absmax
    row move together — a copied page dequantizes identically to its
    source, so copy-on-write never changes what a reader sees."""
    return (tuple(kc.at[dst].set(kc[src]) for kc in kcs),
            tuple(vc.at[dst].set(vc[src]) for vc in vcs),
            tuple(ks.at[dst].set(ks[src]) for ks in kss),
            tuple(vs.at[dst].set(vs[src]) for vs in vss))


# copy-on-write for a shared prefix page: ONE donated dispatch copies the
# page across every layer's pool; src/dst are traced scalars (no
# recompile per page id)
_copy_block_j = jax.jit(_copy_block_impl, donate_argnums=(0, 1))
_copy_block_q8_j = jax.jit(_copy_block_q8_impl,
                           donate_argnums=(0, 1, 2, 3))


def copy_block(caches, src: int, dst: int):
    """Copy physical page ``src`` to ``dst`` in every layer's K/V pool
    (rebinds the PagedKVCache arrays in place; an int8 pool's scale
    rows travel with their pages)."""
    kcs = tuple(c.key_cache for c in caches)
    vcs = tuple(c.value_cache for c in caches)
    if getattr(caches[0], "quantized", False):
        kss = tuple(c.key_scale for c in caches)
        vss = tuple(c.value_scale for c in caches)
        new_k, new_v, new_ks, new_vs = _copy_block_q8_j(
            kcs, vcs, kss, vss, jnp.asarray(src, jnp.int32),
            jnp.asarray(dst, jnp.int32))
        for c, kc, vc, ks, vs in zip(caches, new_k, new_v, new_ks,
                                     new_vs):
            c.key_cache = kc
            c.value_cache = vc
            c.key_scale = ks
            c.value_scale = vs
        return
    new_k, new_v = _copy_block_j(kcs, vcs, jnp.asarray(src, jnp.int32),
                                 jnp.asarray(dst, jnp.int32))
    for c, kc, vc in zip(caches, new_k, new_v):
        c.key_cache = kc
        c.value_cache = vc


# ---------------------------------------------------------------------------
# KV page migration (round 19): extract_blocks / inject_blocks
# ---------------------------------------------------------------------------
# The packed-operand lesson (r11: a host transfer costs ~a whole
# compiled tiny-model module on CPU — transfer COUNT is the budget)
# applied to page movement: a migration is ONE batched device gather
# whose stacked result crosses device→host in ONE copy per dtype
# (int8 codes + their fp32 scale rows), and an injection is ONE donated
# scatter dispatch whose buffer crosses host→device as one operand per
# dtype — never a per-page / per-layer copy loop.  Page counts pad to a
# pow2 bucket (extract: repeat a real page, sliced off on the host;
# inject: padding routed to the sink page) so compiles stay bounded by
# pool geometry × the log2 bucket set — counted in MIGRATION_COMPILES
# and gated like every other step's compile budget.

MIGRATION_COMPILES = {"extract": 0, "inject": 0}
MIGRATION_TRANSFERS = {"d2h": 0, "h2d": 0}
_MIG_SEEN = set()


def migration_compiles():
    """Snapshot of {extract, inject} trace counts (one per pool
    geometry × pow2 page bucket — the compile-bound gate's source)."""
    return dict(MIGRATION_COMPILES)


def migration_transfers():
    """Snapshot of {d2h, h2d} host payload-copy counts.  Each extract
    adds 1 (fp pools) or 2 (int8: codes + scales) d2h copies; each
    inject the same h2d — O(1) per migration, independent of the page
    count (the bench gate)."""
    return dict(MIGRATION_TRANSFERS)


def _note_mig_compile(kind: str, key: tuple):
    if key not in _MIG_SEEN:
        _MIG_SEEN.add(key)
        MIGRATION_COMPILES[kind] += 1


def _pow2_pages(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _extract_impl(kcs, vcs, ids):
    return jnp.stack([kc[ids] for kc in kcs]
                     + [vc[ids] for vc in vcs])


def _extract_q8_impl(kcs, vcs, kss, vss, ids):
    codes = jnp.stack([kc[ids] for kc in kcs]
                      + [vc[ids] for vc in vcs])
    scales = jnp.stack([ks[ids] for ks in kss]
                       + [vs[ids] for vs in vss])
    return codes, scales


# pure reads — the pools stay valid (extraction happens BEFORE the
# refcounted release on the source engine)
_extract_j = jax.jit(_extract_impl)
_extract_q8_j = jax.jit(_extract_q8_impl)


def _inject_impl(kcs, vcs, codes, ids):
    L = len(kcs)
    return (tuple(kc.at[ids].set(codes[i].astype(kc.dtype))
                  for i, kc in enumerate(kcs)),
            tuple(vc.at[ids].set(codes[L + i].astype(vc.dtype))
                  for i, vc in enumerate(vcs)))


def _inject_q8_impl(kcs, vcs, kss, vss, codes, scales, ids):
    L = len(kcs)
    return (tuple(kc.at[ids].set(codes[i]) for i, kc in enumerate(kcs)),
            tuple(vc.at[ids].set(codes[L + i])
                  for i, vc in enumerate(vcs)),
            tuple(ks.at[ids].set(scales[i])
                  for i, ks in enumerate(kss)),
            tuple(vs.at[ids].set(scales[L + i])
                  for i, vs in enumerate(vss)))


# donated: injection is an in-place HBM write into the target pools,
# exactly like the cache appends (hlo-donation covers this module too)
_inject_j = jax.jit(_inject_impl, donate_argnums=(0, 1))
_inject_q8_j = jax.jit(_inject_q8_impl, donate_argnums=(0, 1, 2, 3))


def extract_blocks(caches, block_ids, n_tokens: int):
    """Serialize physical pages ``block_ids`` out of every layer's pool
    into one contiguous host :class:`~paddle_tpu.ops.paged_attention.
    KVPageBuffer` — ONE batched gather dispatch, ONE device→host copy
    per dtype (int8 codes plus their per-page ``key_scale``/
    ``value_scale`` rows, which live per physical page and travel
    free).  The pools are only read; release the pages through the
    refcounted ``free_sequence`` afterwards."""
    from ..ops.paged_attention import KVPageBuffer
    c0 = caches[0]
    ids = [int(b) for b in block_ids]
    if not ids:
        raise ValueError("extract_blocks needs at least one page")
    n = len(ids)
    n_pad = _pow2_pages(n)
    idv = np.full((n_pad,), ids[0], np.int32)   # pad: re-gather a real
    idv[:n] = ids                               # page, sliced off below
    kcs = tuple(c.key_cache for c in caches)
    vcs = tuple(c.value_cache for c in caches)
    quant = bool(getattr(c0, "quantized", False))
    _note_mig_compile("extract", ("x", len(caches), n_pad,
                                  c0.page_geometry()))
    if quant:
        kss = tuple(c.key_scale for c in caches)
        vss = tuple(c.value_scale for c in caches)
        codes_d, scales_d = _extract_q8_j(kcs, vcs, kss, vss, idv)
        codes = np.asarray(codes_d)
        scales = np.ascontiguousarray(np.asarray(scales_d)[:, :n])
        MIGRATION_TRANSFERS["d2h"] += 2
    else:
        codes = np.asarray(_extract_j(kcs, vcs, idv))
        scales = None
        MIGRATION_TRANSFERS["d2h"] += 1
    return KVPageBuffer(
        codes=np.ascontiguousarray(codes[:, :n]), scales=scales,
        n_pages=n, n_tokens=int(n_tokens), block_size=c0.block_size,
        num_kv_heads=c0.num_kv_heads, head_dim=c0.head_dim,
        num_layers=len(caches), kv_dtype=c0.kv_dtype)


def inject_blocks(caches, buf, dest_blocks):
    """Scatter a :class:`KVPageBuffer`'s pages into ``dest_blocks`` of
    every layer's pool — ONE donated dispatch, the buffer crossing
    host→device as one operand per dtype.  ``dest_blocks`` must come
    from the target pool's refcounted ``allocate_block`` path (the
    caller owns the references).  Geometry (layer count, page shape,
    ``kv_dtype``) must match the buffer's header exactly — a mismatch
    (e.g. int8 pages into an fp32 pool) raises a clear ValueError here,
    never a dtype failure inside the trace."""
    c0 = caches[0]
    here = (len(caches),) + c0.page_geometry()
    want = buf.geometry()
    if here != want:
        raise ValueError(
            "inject_blocks: pool geometry mismatch — buffer was "
            "extracted from (layers, block_size, kv_heads, head_dim, "
            "kv_dtype)=%r but the target pool is %r; KV pages only "
            "migrate between engines with identical pool geometry "
            "(including kv_dtype — int8 codes are meaningless in an "
            "fp pool and vice versa)" % (want, here))
    n = buf.n_pages
    if len(dest_blocks) != n:
        raise ValueError(
            "inject_blocks: buffer holds %d page(s) but %d destination "
            "block(s) were allocated" % (n, len(dest_blocks)))
    n_pad = _pow2_pages(n)
    sink = getattr(c0, "sink", -1)
    pad_id = sink if sink >= 0 else int(dest_blocks[-1])
    idv = np.full((n_pad,), pad_id, np.int32)
    idv[:n] = [int(b) for b in dest_blocks]
    codes, scales = buf.codes, buf.scales
    if n_pad != n:
        # pad rows route to the sink page (or re-write the last page
        # with its own content) — garbage-on-garbage, like every other
        # fixed-shape padding in the serving steps
        rep = np.repeat(codes[:, -1:], n_pad - n, axis=1)
        codes = np.concatenate([codes, rep], axis=1)
        if scales is not None:
            srep = np.repeat(scales[:, -1:], n_pad - n, axis=1)
            scales = np.concatenate([scales, srep], axis=1)
    kcs = tuple(c.key_cache for c in caches)
    vcs = tuple(c.value_cache for c in caches)
    quant = bool(getattr(c0, "quantized", False))
    _note_mig_compile("inject", ("i", len(caches), n_pad,
                                 c0.page_geometry()))
    if quant:
        kss = tuple(c.key_scale for c in caches)
        vss = tuple(c.value_scale for c in caches)
        new_k, new_v, new_ks, new_vs = _inject_q8_j(
            kcs, vcs, kss, vss, codes, scales, idv)
        MIGRATION_TRANSFERS["h2d"] += 2
        for c, kc, vc, ks, vs in zip(caches, new_k, new_v, new_ks,
                                     new_vs):
            c.key_cache = kc
            c.value_cache = vc
            c.key_scale = ks
            c.value_scale = vs
        return
    new_k, new_v = _inject_j(kcs, vcs, codes, idv)
    MIGRATION_TRANSFERS["h2d"] += 1
    for c, kc, vc in zip(caches, new_k, new_v):
        c.key_cache = kc
        c.value_cache = vc


def compiled_cost_stats(lowered, tokens: int) -> dict:
    """FLOPs + byte traffic of ONE compiled serving-step module — the
    serving twin of ``TrainStep.compiled_stats`` (the round-9 MFU
    source), shared by all three step classes.  ``tokens`` is the
    launch's packed token capacity (a budget-``T`` mixed launch
    advances up to T real tokens; padding spans do sink-page work the
    device genuinely executes, so per-token numbers are the honest
    full-launch amortization).  XLA reports PER-DEVICE numbers, so the
    consumer divides by per-chip peak — never peak x device_count.
    Every field is best-effort: a backend without cost_analysis just
    yields fewer keys."""
    stats = {"tokens": int(tokens), "source": "cost_analysis"}
    compiled = lowered.compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for src, dst in (("flops", "flops"),
                         ("bytes accessed", "bytes_accessed")):
            if ca.get(src):
                stats[dst] = float(ca[src])
    except Exception:                                 # noqa: BLE001
        pass
    try:
        ma = compiled.memory_analysis()
        for attr, dst in (("temp_size_in_bytes", "temp_bytes"),
                          ("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes")):
            v = getattr(ma, attr, None)
            if v:
                stats[dst] = int(v)
    except Exception:                                 # noqa: BLE001
        pass
    if tokens > 0:
        if stats.get("flops"):
            stats["flops_per_token"] = stats["flops"] / tokens
        if stats.get("bytes_accessed"):
            stats["hbm_bytes_per_token"] = \
                stats["bytes_accessed"] / tokens
    return stats


class PrefillStep:
    """Bucketed/chunked prefill compiled into one donated XLA module per
    LENGTH BUCKET — the prefill analog of ``DecodeStep``.

    ``__call__(tokens, start, n_valid, block_table_row)`` runs one
    padded chunk of a prompt: embeds the [1, C] bucket-padded token
    block, and per layer projects, applies RoPE at global positions
    ``start + i``, scatters the chunk's K/V into cache pages (padding
    routed to the sink page), and attends causally over everything
    cached so far (earlier chunks / shared prefix pages included).  The
    final hidden state is sliced to the LAST VALID position before the
    LM head — the [C, V] logits block is never materialized — and the
    next token is sampled (greedy) on device, so the step's only host
    traffic is one int32 scalar.

    Shape policy: chunk offset (``start``) and fill level (``n_valid``)
    are traced scalars, so total prefill compiles are bounded by the
    BUCKET COUNT — not the prompt-length distribution, not the chunk
    position, not the prefix-hit split.  ``compile_counts`` maps bucket
    width -> trace count (tests and the bench gate on it).
    """

    def __init__(self, model, caches: List, bt_width: int,
                 mesh=None, sharding=None,
                 tp: Optional[TPContext] = None,
                 weight_qparams=None, quant_collectives: bool = False,
                 sampling: bool = False):
        self.model = model
        self.caches = caches
        self.cfg = model.config
        self.bt_width = bt_width
        self.sampling = bool(sampling)
        self.sink = caches[0].sink
        if self.sink < 0:
            raise ValueError("PrefillStep needs a sink page "
                             "(PagedKVCache(sink_block=True)) to mask "
                             "bucket padding writes")
        self._tp = _resolve_tp(model, mesh, sharding, tp)
        self._quant_kv = bool(getattr(caches[0], "quantized", False))
        self._wq = weight_qparams
        self._q8_gather = bool(quant_collectives)
        _ensure_quant_specs(self._tp, weight_qparams)
        self._param_tensors = dict(model.state_dict())
        self._fns = {}                 # bucket width -> jitted step
        self.compile_counts = {}       # bucket width -> trace count

    @property
    def total_compiles(self) -> int:
        return sum(self.compile_counts.values())

    def collective_bytes(self, C: int):
        """Per-chip collective payload of one sharded chunk of bucket
        width ``C`` ({} when single-chip; one logits row)."""
        if self._tp is None:
            return {}
        return self._tp.collective_bytes(self.cfg, C, 1,
                                         quant_gather=self._q8_gather)

    def _build(self, C: int):
        from ..autograd.tape import no_grad
        from ..ops.paged_attention import (chunk_prefill_attention,
                                           write_chunk_kv,
                                           write_chunk_kv_q8)
        from ..ops.pallas_kernels import (rope_qkv_epilogue,
                                          rope_tables_for_positions)
        model = self.model
        cfg = self.cfg
        llama = _inner_model(model)
        tp = self._tp
        deg = tp.degree if tp is not None else 1
        H = cfg.num_attention_heads // deg      # this chip's head shard
        Hkv = cfg.num_key_value_heads // deg
        D = cfg.hidden_size // cfg.num_attention_heads
        scale = 1.0 / math.sqrt(D)
        sink = self.sink
        quant_kv = self._quant_kv
        q8_gather = self._q8_gather
        pdtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        cp_axis = tp.cp_axis if tp is not None else None
        cp_deg = tp.cp_degree if tp is not None else 1
        if cp_deg > 1:
            from ..ops.online_softmax import cross_chip_merge
            from ..ops.paged_attention import (
                chunk_prefill_attention_partial, write_ragged_kv)

        sampling = self.sampling
        if sampling:
            from ..ops.sampling import sample_logits

        def step(params, tokens, start, n_valid, bt, samp, kcs, vcs,
                 kss, vss):
            self.compile_counts[C] = self.compile_counts.get(C, 0) + 1
            params = _materialize_params(params, pdtype)
            new_kcs, new_vcs = [], []
            new_kss, new_vss = [], []
            with model.bind_state(params), no_grad():
                x = _embed(llama, tokens, tp)
                if cfg.dtype == "bfloat16":
                    x = x.astype("bfloat16")
                pos = start + jnp.arange(C, dtype=jnp.int32)
                cos_t, sin_t = rope_tables_for_positions(
                    pos, D, cfg.rope_theta)
                for li, (layer, kc, vc) in enumerate(
                        zip(llama.layers, kcs, vcs)):
                    h = layer.input_layernorm(x)
                    attn = layer.self_attn
                    q = attn.q_proj(h).reshape([1, C, H, D])
                    k = attn.k_proj(h).reshape([1, C, Hkv, D])
                    v = attn.v_proj(h).reshape([1, C, Hkv, D])
                    qv, kv_, k_amax, v_amax = rope_qkv_epilogue(
                        q._value[0], k._value[0], v._value[0],
                        cos_t, sin_t, with_amax=quant_kv)
                    if quant_kv:
                        kc, vc, ks, vs = write_chunk_kv_q8(
                            kv_[None], v._value, kc, vc, kss[li],
                            vss[li], bt, start, n_valid, sink,
                            k_amax=k_amax, v_amax=v_amax)
                        new_kss.append(ks)
                        new_vss.append(vs)
                    else:
                        ks = vs = None
                        if cp_deg > 1:
                            # chunked prefill writes ONLY the owning
                            # stripe (sequence-parallel scatter): the
                            # global destination mirrors write_chunk_kv
                            # at the GLOBAL block size, then the
                            # stripe-local translation routes non-owned
                            # rows to this chip's sink stripe
                            bsl = kc.shape[1]
                            gbs = bsl * cp_deg
                            idx_c = jnp.arange(C, dtype=jnp.int32)
                            pos_c = start.astype(jnp.int32) + idx_c
                            blk_g = bt[0, pos_c // gbs]
                            valid = idx_c < n_valid
                            blk_g = jnp.where(valid, blk_g,
                                              jnp.int32(sink))
                            goff = jnp.where(valid, pos_c % gbs, 0)
                            blk, off = _cp_local_dest(
                                blk_g, goff, bsl, cp_axis, sink)
                            kc, vc = write_ragged_kv(
                                kv_, v._value[0], kc, vc, blk, off)
                        else:
                            kc, vc = write_chunk_kv(
                                kv_[None], v._value, kc, vc, bt, start,
                                n_valid, sink)
                    new_kcs.append(kc)
                    new_vcs.append(vc)
                    if cp_deg > 1:
                        bsl = kc.shape[1]
                        stripe = jax.lax.axis_index(cp_axis) * bsl
                        o_p, m_p, l_p = chunk_prefill_attention_partial(
                            qv[None], kc, vc, bt, start, scale,
                            stripe, bsl * cp_deg)
                        out = cross_chip_merge(
                            o_p[0], m_p[0], l_p[0], cp_axis)[None]
                    else:
                        out = chunk_prefill_attention(
                            qv[None], kc, vc, bt, start, scale,
                            key_scale=ks, value_scale=vs)
                    out = Tensor._from_value(out.reshape(1, C, H * D))
                    x = x + _tp_psum(attn.o_proj(out), tp)
                    h2 = layer.post_attention_layernorm(x)
                    x = x + _ffn(layer, h2, tp)
                x = llama.norm(x)
                # only the last VALID position reaches the LM head:
                # [1, 1, h] @ [h, V], never the [C, V] logits block
                last = jax.lax.dynamic_slice_in_dim(
                    x._value, n_valid - 1, 1, axis=1)
                last = Tensor._from_value(last)
                if model.lm_head is None:
                    from ..ops.linalg import matmul
                    logits = matmul(last, llama.embed_tokens.weight,
                                    transpose_y=True)
                else:
                    logits = model.lm_head(last)
                logits = _tp_logits(logits, tp, q8=q8_gather)
            if samp is None:
                nxt = jnp.argmax(logits._value[0, 0]
                                 .astype(jnp.float32)).astype(jnp.int32)
            else:
                # first-token sample: counter = the prompt length
                # start + n_valid (= the sampled token's position)
                t, k, p, sd = _samp_knobs(samp[None, :])
                toks = sample_logits(logits._value[:, 0, :], t, k,
                                        p, sd, (start + n_valid)[None])
                nxt = toks[0]
            return (nxt, tuple(new_kcs), tuple(new_vcs),
                    tuple(new_kss), tuple(new_vss))

        if sampling:
            fn, donate, n_repl = step, (6, 7, 8, 9), 5
        else:
            def fn(params, tokens, start, n_valid, bt, kcs, vcs, kss,
                   vss):
                return step(params, tokens, start, n_valid, bt, None,
                            kcs, vcs, kss, vss)
            donate, n_repl = (5, 6, 7, 8), 4
        if tp is None:
            return jax.jit(fn, donate_argnums=donate)
        return _wrap_sharded(fn, tp, self._wq or self._param_tensors,
                             len(self.caches), n_repl=n_repl,
                             donate=donate,
                             quant_kv=quant_kv)

    def aot_lower(self, C: int):
        """AOT-lower (never execute) one bucket-``C`` prefill module
        with zero host operands — the graftlint hlo-contract artifact
        (donation aliases the pools, no f64, the chunk host-operand
        count stays pinned at 4)."""
        fn = self._fns.get(C)
        if fn is None:
            fn = self._fns[C] = self._build(C)
        params = _step_params(self._param_tensors, self._tp, self._wq)
        kcs = tuple(c.key_cache for c in self.caches)
        vcs = tuple(c.value_cache for c in self.caches)
        kss, vss = _cache_scales(self.caches, self._quant_kv)
        args = [params,
                jnp.zeros((1, C), jnp.int32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(1, jnp.int32),
                jnp.zeros((1, self.bt_width), jnp.int32)]
        if self.sampling:
            args.append(jnp.zeros((4,), jnp.int32))
        return fn.lower(*args, kcs, vcs, kss, vss)

    def compiled_stats(self, C: int) -> dict:
        """Cached ``cost_analysis`` of one bucket-``C`` compiled chunk
        (see :func:`compiled_cost_stats`; same cached jit as the real
        call, so a later dispatch does not re-trace)."""
        cache = getattr(self, "_cost_stats", None)
        if cache is None:
            cache = self._cost_stats = {}
        if C not in cache:
            cache[C] = compiled_cost_stats(self.aot_lower(C), C)
        return cache[C]

    def __call__(self, tokens, start: int, n_valid: int,
                 block_table_row, samp=None) -> int:
        """tokens: [1, C] int32 bucket-padded; returns the next token
        after position start+n_valid-1 (meaningful on the final chunk;
        earlier chunks' samples are discarded by the engine).  samp
        (sampling steps): [4] int32 knobs for the request."""
        C = int(np.asarray(tokens).shape[1])
        fn = self._fns.get(C)
        if fn is None:
            fn = self._fns[C] = self._build(C)
        params = _step_params(self._param_tensors, self._tp, self._wq)
        kcs = tuple(c.key_cache for c in self.caches)
        vcs = tuple(c.value_cache for c in self.caches)
        kss, vss = _cache_scales(self.caches, self._quant_kv)
        args = [params,
                jnp.asarray(np.asarray(tokens, np.int32)),
                jnp.asarray(start, jnp.int32),
                jnp.asarray(n_valid, jnp.int32),
                jnp.asarray(np.asarray(block_table_row), jnp.int32)]
        if self.sampling:
            if samp is None:
                samp = np.zeros((4,), np.int32)        # greedy default
            args.append(jnp.asarray(np.asarray(samp, np.int32)))
        nxt, new_kcs, new_vcs, new_kss, new_vss = fn(
            *args, kcs, vcs, kss, vss)
        _rebind_caches(self.caches, new_kcs, new_vcs, new_kss, new_vss)
        return int(nxt)


class MixedStep:
    """ONE compiled donated XLA module per TOTAL-TOKEN BUDGET that
    advances ANY admission mix — active decode slots and pending prefill
    chunks together — in a single launch (Ragged Paged Attention,
    arXiv:2604.15464).

    The engine packs its work into a ragged token batch: every running
    slot contributes a length-1 decode span, every prefilling slot a
    length-C chunk span, concatenated on the token axis and padded to
    the smallest budget in a small geometric set.  The traced body
    embeds the packed tokens, and per layer projects, applies RoPE at
    each token's GLOBAL position, scatters K/V into cache pages (padding
    routed to the sink page — ``write_ragged_kv``), and runs ragged
    paged attention (Pallas kernel on TPU, XLA gather reference on CPU)
    where each span attends causally over its own page list.  Each
    span's LAST VALID row is gathered before the LM head — the [T, V]
    logits block is never materialized — and greedy-sampled on device,
    so the step's only host traffic is one [max_spans] int32 fetch.

    Shape policy: every span descriptor (offset, length, kv length,
    page table, sample row, per-token write destination) is TRACED DATA;
    the only traced SHAPE is the token budget, so total compiles are
    bounded by the budget-set size across any occupancy/admission churn
    — there is no separate prefill/decode module split and no per-chunk
    engine round.  ``compile_counts`` maps budget -> trace count (tests
    and the bench gate on it).
    """

    def __init__(self, model, caches: List, bt_width: int,
                 max_spans: int, span_q: int,
                 use_pallas: Optional[bool] = None,
                 mesh=None, sharding=None,
                 tp: Optional[TPContext] = None,
                 weight_qparams=None, quant_collectives: bool = False,
                 sampling: bool = False, spec_k: int = 0,
                 return_probs: bool = False):
        from ..ops.paged_attention import _HAS_PLTPU, _on_tpu
        self.model = model
        self.caches = caches
        self.cfg = model.config
        self.bt_width = bt_width
        self.max_spans = max_spans
        self.span_q = max(1, int(span_q))   # static max span length
        self.sampling = bool(sampling)
        self.spec_k = int(spec_k)
        self.return_probs = bool(return_probs)
        if self.return_probs and not self.sampling:
            raise ValueError(
                "MixedStep return_probs=True exists for the SAMPLED "
                "draft role (the verifier's residual needs the draft's "
                "filtered distribution); a greedy draft is a delta — "
                "construct with sampling=True or drop return_probs")
        if self.spec_k and self.return_probs:
            raise ValueError(
                "MixedStep cannot be verifier (spec_k) and draft "
                "(return_probs) at once")
        if self.spec_k and self.span_q < self.spec_k + 1:
            raise ValueError(
                "span_q=%d cannot cover a length-%d verify span "
                "(spec_k=%d): the Pallas kernel's static span window "
                "must be >= every q_len" % (self.span_q,
                                            self.spec_k + 1,
                                            self.spec_k))
        # span-row tail past the block-table columns: the 4 standard
        # descriptors, +1 n_draft column under spec, +4 bitcast
        # sampling-knob columns under sampling.  4 == the round-13
        # layout, so default packs are byte-identical.
        self.row_extra = (4 + (1 if self.spec_k else 0)
                          + (4 if self.sampling else 0))
        self.sink = caches[0].sink
        if self.sink < 0:
            raise ValueError("MixedStep needs a sink page "
                             "(PagedKVCache(sink_block=True)) to mask "
                             "budget-padding writes")
        if use_pallas is None:
            use_pallas = _HAS_PLTPU and _on_tpu()
        self.use_pallas = use_pallas
        self._tp = _resolve_tp(model, mesh, sharding, tp)
        if self.spec_k and self._tp is not None:
            raise ValueError(
                "speculative verification (spec_k) is single-chip: the "
                "draft engine runs unsharded, so a tensor-parallel "
                "verifier would mix placements — drop mesh/sharding or "
                "drop the draft")
        self._quant_kv = bool(getattr(caches[0], "quantized", False))
        if self._tp is not None and self._tp.cp_degree > 1 \
                and self._quant_kv:
            from .spmd import validate_cp_serving
            validate_cp_serving(self._tp.cp_degree,
                                caches[0].block_size, quantized_kv=True)
        self._wq = weight_qparams
        self._q8_gather = bool(quant_collectives)
        _ensure_quant_specs(self._tp, weight_qparams)
        self._param_tensors = dict(model.state_dict())
        self._fns = {}                 # token budget -> jitted step
        self.compile_counts = {}       # token budget -> trace count

    @property
    def total_compiles(self) -> int:
        return sum(self.compile_counts.values())

    def collective_bytes(self, T: int):
        """Per-chip collective payload of one sharded step at budget
        ``T`` ({} when single-chip; see
        ``spmd.TPContext.collective_bytes``)."""
        if self._tp is None:
            return {}
        return self._tp.collective_bytes(self.cfg, T, self.max_spans,
                                         quant_gather=self._q8_gather)

    def _build(self, T: int):
        from ..autograd.tape import no_grad
        from ..ops.paged_attention import (_ragged_attention_xla,
                                           write_ragged_kv,
                                           write_ragged_kv_q8)
        from ..ops.pallas_kernels import (rope_qkv_epilogue,
                                          rope_tables_for_positions)
        model = self.model
        cfg = self.cfg
        llama = _inner_model(model)
        tp = self._tp
        deg = tp.degree if tp is not None else 1
        # under tensor parallelism the traced body sees this chip's
        # LOCAL head shard: projections produce H/tp query and Hkv/tp
        # kv heads, and the (head-sharded) page pools match
        H = cfg.num_attention_heads // deg
        Hkv = cfg.num_key_value_heads // deg
        D = cfg.hidden_size // cfg.num_attention_heads
        scale = 1.0 / math.sqrt(D)
        span_q = min(self.span_q, T)
        use_pallas = self.use_pallas
        quant_kv = self._quant_kv
        q8_gather = self._q8_gather
        pdtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        cp_axis = tp.cp_axis if tp is not None else None
        cp_deg = tp.cp_degree if tp is not None else 1
        sink = self.sink
        if use_pallas and cp_deg <= 1:
            from ..ops.pallas_kernels import _ragged_paged_attention_pallas

        if cp_deg > 1:
            # context parallel (round 22): each chip attends over its
            # LOCAL slot stripe of every page with the partial-softmax
            # kernel variant, then the `(o, m, l)` triples merge across
            # the cp axis (ops/online_softmax.cross_chip_merge — one
            # all_gather of the three small rows).  XLA path only for
            # now: the per-stripe Pallas launch is the TPU follow-up.
            from ..ops.online_softmax import cross_chip_merge
            from ..ops.paged_attention import _ragged_attention_xla_partial

            def attn(q, kc, vc, bt, q_off, q_len, kv_len,
                     ks=None, vs=None):
                bsl = kc.shape[1]
                stripe = jax.lax.axis_index(cp_axis) * bsl
                o, m, l = _ragged_attention_xla_partial(
                    q, kc, vc, bt, q_off, q_len, kv_len, scale,
                    stripe, bsl * cp_deg)
                return cross_chip_merge(o, m, l, cp_axis)
        else:
            def attn(q, kc, vc, bt, q_off, q_len, kv_len,
                     ks=None, vs=None):
                if use_pallas:
                    return _ragged_paged_attention_pallas(
                        q, kc, vc, bt, q_off, q_len, kv_len, scale,
                        span_q=span_q, key_scale=ks, value_scale=vs)
                return _ragged_attention_xla(q, kc, vc, bt, q_off,
                                             q_len, kv_len, scale,
                                             ks, vs)

        W = self.bt_width
        S = self.max_spans
        EX = self.row_extra
        sampling = self.sampling
        spec_k = self.spec_k
        return_probs = self.return_probs
        if sampling or spec_k:
            from ..ops.sampling import (filtered_probs, sample_logits,
                                        spec_verify)

        def step(params, pack, q_probs, kcs, vcs, kss, vss):
            self.compile_counts[T] = self.compile_counts.get(T, 0) + 1
            # unpack the single host buffer (free at trace level —
            # slices of a constant layout): rows 0-3 of the leading
            # [4, T] block are tokens / positions / dest block / dest
            # offset; the trailing [S, W+EX] block is the block table
            # columns then q_offset / q_len / kv_len / sample_row
            # (+ n_draft under spec, + the 4 bitcast sampling-knob
            # columns under sampling).  ONE device_put per step instead
            # of nine — transfer count, not byte count, is the
            # decode-parity budget at low occupancy.
            tok_tab = pack[:4 * T].reshape(4, T)
            span_tab = pack[4 * T:].reshape(S, W + EX)
            tokens = tok_tab[0]
            positions = tok_tab[1]
            dest_blocks = tok_tab[2]
            dest_offsets = tok_tab[3]
            if cp_deg > 1:
                # the host packs GLOBAL in-page offsets; each chip
                # keeps only the rows its slot stripe owns (the rest
                # go to its sink stripe) — the scatter itself and the
                # packed-operand layout are unchanged
                dest_blocks, dest_offsets = _cp_local_dest(
                    dest_blocks, dest_offsets, kcs[0].shape[1],
                    cp_axis, sink)
            bt = span_tab[:, :W]
            q_offsets = span_tab[:, W]
            q_lens = span_tab[:, W + 1]
            kv_lens = span_tab[:, W + 2]
            sample_rows = span_tab[:, W + 3]
            col = W + 4
            if spec_k:
                n_draft = span_tab[:, col]
                col += 1
            if sampling:
                s_t, s_k, s_p, s_sd = _samp_knobs(span_tab[:, col:col + 4])
            params = _materialize_params(params, pdtype)
            new_kcs, new_vcs = [], []
            new_kss, new_vss = [], []
            with model.bind_state(params), no_grad():
                x = _embed(llama, tokens[None, :], tp)         # [1, T, h]
                if cfg.dtype == "bfloat16":
                    x = x.astype("bfloat16")
                # rope tables built ONCE per step (positions are
                # layer-invariant) and consumed by the fused epilogue
                # in every layer
                cos_t, sin_t = rope_tables_for_positions(
                    positions, D, cfg.rope_theta)
                for li, (layer, kc, vc) in enumerate(
                        zip(llama.layers, kcs, vcs)):
                    h = layer.input_layernorm(x)
                    at = layer.self_attn
                    q = at.q_proj(h).reshape([1, T, H, D])
                    k = at.k_proj(h).reshape([1, T, Hkv, D])
                    v = at.v_proj(h).reshape([1, T, Hkv, D])
                    # fused RoPE+QKV epilogue: rope(q), rope(k) and the
                    # quantize-on-write absmax rows in ONE pass over
                    # the projection outputs
                    qv, kv_, k_amax, v_amax = rope_qkv_epilogue(
                        q._value[0], k._value[0], v._value[0],
                        cos_t, sin_t, with_amax=quant_kv)
                    if quant_kv:
                        kc, vc, ks, vs = write_ragged_kv_q8(
                            kv_, v._value[0], kc, vc, kss[li],
                            vss[li], dest_blocks, dest_offsets,
                            k_amax=k_amax, v_amax=v_amax)
                        new_kss.append(ks)
                        new_vss.append(vs)
                    else:
                        ks = vs = None
                        kc, vc = write_ragged_kv(
                            kv_, v._value[0], kc, vc,
                            dest_blocks, dest_offsets)
                    new_kcs.append(kc)
                    new_vcs.append(vc)
                    out = attn(qv, kc, vc, bt, q_offsets,
                               q_lens, kv_lens, ks, vs)
                    out = Tensor._from_value(out.reshape(1, T, H * D))
                    x = x + _tp_psum(at.o_proj(out), tp)
                    h2 = layer.post_attention_layernorm(x)
                    x = x + _ffn(layer, h2, tp)
                x = llama.norm(x)
                # only each span's sampled rows reach the LM head:
                # one row per span normally ([max_spans, 1, h] @
                # [h, V]); under spec_k each span's K+1 verify rows
                # plus its last-valid row ([S*(K+2), 1, h]) — the
                # [T, V] logits block is never materialized either way
                if spec_k:
                    vrow = (q_offsets[:, None]
                            + jnp.arange(spec_k + 1,
                                         dtype=jnp.int32)[None, :])
                    last = q_offsets + jnp.maximum(q_lens - 1, 0)
                    vrow = jnp.minimum(vrow, last[:, None])
                    rows_idx = jnp.clip(
                        jnp.concatenate([vrow, sample_rows[:, None]],
                                        axis=1).reshape(-1), 0, T - 1)
                else:
                    rows_idx = sample_rows
                rows = Tensor._from_value(
                    x._value[0][rows_idx][:, None, :])
                if model.lm_head is None:
                    from ..ops.linalg import matmul
                    logits = matmul(rows, llama.embed_tokens.weight,
                                    transpose_y=True)
                else:
                    logits = model.lm_head(rows)
                logits = _tp_logits(logits, tp, q8=q8_gather)
            lv = logits._value[:, 0, :].astype(jnp.float32)
            if spec_k:
                # speculative verify: rows [:, :K+1] feed the
                # accept/reject scan, row K+1 is the plain-span sample
                lv3 = lv.reshape(S, spec_k + 2, -1)
                didx = jnp.clip(
                    q_offsets[:, None] + 1
                    + jnp.arange(spec_k, dtype=jnp.int32)[None, :],
                    0, T - 1)
                d_toks = tokens[didx]          # the spans' fed drafts
                base_pos = kv_lens - q_lens + 1
                if sampling:
                    q = jnp.stack(q_probs, axis=1)        # [S, K, V]
                    n_acc, e_v = spec_verify(
                        lv3[:, :spec_k + 1], d_toks, n_draft, s_t, s_k,
                        s_p, s_sd, base_pos, q)
                    e_p = sample_logits(lv3[:, spec_k + 1], s_t,
                                           s_k, s_p, s_sd, kv_lens)
                else:
                    zf = jnp.zeros((S,), jnp.float32)
                    zi = jnp.zeros((S,), jnp.int32)
                    n_acc, e_v = spec_verify(
                        lv3[:, :spec_k + 1], d_toks, n_draft, zf, zi,
                        zf, zi, base_pos)
                    e_p = jnp.argmax(lv3[:, spec_k + 1],
                                     axis=-1).astype(jnp.int32)
                nxt = jnp.where(n_draft > 0, e_v, e_p)
                return (nxt, n_acc, tuple(new_kcs), tuple(new_vcs),
                        tuple(new_kss), tuple(new_vss))
            if sampling:
                # counter = kv_len — the sampled token's global
                # position, the SAME counter the split steps use, so
                # seeded tokens agree across engines
                nxt = sample_logits(lv, s_t, s_k, s_p, s_sd,
                                       kv_lens)
            else:
                nxt = jnp.argmax(lv, axis=-1).astype(jnp.int32)
            if return_probs:
                return (nxt, filtered_probs(lv, s_t, s_k, s_p),
                        tuple(new_kcs), tuple(new_vcs),
                        tuple(new_kss), tuple(new_vss))
            return (nxt, tuple(new_kcs), tuple(new_vcs),
                    tuple(new_kss), tuple(new_vss))

        if spec_k and sampling:
            fn, donate = step, (3, 4, 5, 6)
        else:
            # no draft-probs operand: same pytree as round 13 when
            # sampling/spec are both off
            def fn(params, pack, kcs, vcs, kss, vss):
                return step(params, pack, None, kcs, vcs, kss, vss)
            donate = (2, 3, 4, 5)
        if tp is None:
            return jax.jit(fn, donate_argnums=donate)
        return _wrap_sharded(fn, tp, self._wq or self._param_tensors,
                             len(self.caches), n_repl=1,
                             donate=donate,
                             quant_kv=self._quant_kv)

    def __call__(self, tokens, positions, dest_blocks, dest_offsets,
                 q_offsets, q_lens, kv_lens, block_tables,
                 sample_rows) -> np.ndarray:
        """tokens/positions/dest_*: [T] packed per-token arrays (T must
        be a configured budget); q_offsets/q_lens/kv_lens/sample_rows:
        [max_spans]; block_tables: [max_spans, bt_width].  Returns the
        [max_spans] int32 greedy samples (row i = span i's next token;
        padding spans and non-final chunks are discarded by the
        engine)."""
        T = int(np.asarray(tokens).shape[0])
        pack, tok_tab, span_tab = self.new_pack(T)
        tok_tab[0] = tokens
        tok_tab[1] = positions
        tok_tab[2] = dest_blocks
        tok_tab[3] = dest_offsets
        W = self.bt_width
        span_tab[:, :W] = block_tables
        span_tab[:, W] = q_offsets
        span_tab[:, W + 1] = q_lens
        span_tab[:, W + 2] = kv_lens
        span_tab[:, W + 3] = sample_rows
        return self.call_packed(pack, T)

    def new_pack(self, T: int):
        """Allocate the step's single host buffer: ``(pack, tok_tab,
        span_tab)`` where tok_tab [4, T] (rows tokens / positions /
        dest block / dest offset) and span_tab
        [max_spans, bt_width+row_extra] (block-table columns then
        q_offset / q_len / kv_len / sample_row, + n_draft under spec,
        + the 4 bitcast sampling-knob columns under sampling) are VIEWS
        into pack — fill them, then hand pack to ``call_packed``.  The
        extra tail columns come pre-zeroed (greedy, no drafts), so a
        caller that only fills the round-13 layout stays correct."""
        S, W = self.max_spans, self.bt_width
        pack = np.empty(4 * T + S * (W + self.row_extra), np.int32)
        span_tab = pack[4 * T:].reshape(S, W + self.row_extra)
        if self.row_extra > 4:
            span_tab[:, W + 4:] = 0
        return pack, pack[:4 * T].reshape(4, T), span_tab

    def aot_lower(self, T: int):
        """AOT-lower (never execute) one budget-``T`` module with a
        zero pack and the caches' current pools — the artifact the
        graftlint hlo-contract pass asserts over (donation aliases the
        pools, no f64 op, ONE packed int32 host operand of the pinned
        length).  Uses the same cached jit as ``call_packed``, so a
        subsequent real call does not re-trace."""
        fn = self._fns.get(T)
        if fn is None:
            fn = self._fns[T] = self._build(T)
        pack, _tok, _span = self.new_pack(T)
        pack[:] = 0
        params = _step_params(self._param_tensors, self._tp, self._wq)
        kcs = tuple(c.key_cache for c in self.caches)
        vcs = tuple(c.value_cache for c in self.caches)
        kss, vss = _cache_scales(self.caches, self._quant_kv)
        args = [params, jnp.asarray(pack)]
        if self.spec_k and self.sampling:
            V = self.cfg.vocab_size
            args.append(tuple(
                jnp.zeros((self.max_spans, V), jnp.float32)
                for _ in range(self.spec_k)))
        return fn.lower(*args, kcs, vcs, kss, vss)

    def compiled_stats(self, T: int) -> dict:
        """Cached ``cost_analysis`` of one budget-``T`` compiled mixed
        launch (see :func:`compiled_cost_stats`) — the capacity plane's
        per-token FLOPs/HBM source.  Reuses the ``call_packed`` jit
        cache, so a later real call does not re-trace."""
        cache = getattr(self, "_cost_stats", None)
        if cache is None:
            cache = self._cost_stats = {}
        if T not in cache:
            cache[T] = compiled_cost_stats(self.aot_lower(T), T)
        return cache[T]

    def call_packed(self, pack: np.ndarray, T: int, q_probs=None):
        """Dispatch one pre-packed step buffer (see ``new_pack``).  The
        nine per-step operands cross the host link as ONE int32
        device_put: transfer count, not byte count, is what decode
        parity with the split DecodeStep is made of at low occupancy.

        Returns the [max_spans] int32 sample array; a verifier
        (``spec_k``) returns ``(tokens, n_acc)`` and takes ``q_probs``
        (a tuple of K device-resident [max_spans, V] draft
        distributions) when sampled; a draft (``return_probs``)
        returns ``(tokens, probs)`` with probs left ON DEVICE."""
        fn = self._fns.get(T)
        if fn is None:
            fn = self._fns[T] = self._build(T)
        params = _step_params(self._param_tensors, self._tp, self._wq)
        kcs = tuple(c.key_cache for c in self.caches)
        vcs = tuple(c.value_cache for c in self.caches)
        kss, vss = _cache_scales(self.caches, self._quant_kv)
        args = [params, jnp.asarray(pack)]
        if self.spec_k and self.sampling:
            if q_probs is None:
                raise ValueError(
                    "sampled speculative verify needs the draft's "
                    "q_probs tuple (zeros when no span drafts)")
            args.append(tuple(q_probs))
        out = fn(*args, kcs, vcs, kss, vss)
        if self.spec_k:
            nxt, n_acc = out[0], out[1]
            _rebind_caches(self.caches, *out[2:])
            return np.asarray(nxt), np.asarray(n_acc)
        if self.return_probs:
            nxt, probs = out[0], out[1]
            _rebind_caches(self.caches, *out[2:])
            return np.asarray(nxt), probs
        _rebind_caches(self.caches, *out[1:])
        return np.asarray(out[0])


class DecodeStep:
    """Compile the whole per-token decode into one donated-buffer call.

    ``__call__(tokens, seq_lens, block_tables)`` advances every slot by
    one token: appends the previous token's K/V at position seq_len,
    attends over seq_len+1 cached tokens, and returns the greedy next
    token per slot as a host int32 array (the step's only host fetch).
    The per-layer caches are read from — and rebound onto — the
    PagedKVCache objects handed to the constructor.
    """

    def __init__(self, model, caches: List, use_pallas: Optional[bool]
                 = None, mesh=None, sharding=None,
                 tp: Optional[TPContext] = None,
                 weight_qparams=None, quant_collectives: bool = False,
                 sampling: bool = False):
        from ..ops.paged_attention import _HAS_PLTPU, _on_tpu
        self.model = model
        self.caches = caches
        self.cfg = model.config
        if use_pallas is None:
            use_pallas = _HAS_PLTPU and _on_tpu()
        self.use_pallas = use_pallas
        self.sampling = bool(sampling)
        self._tp = _resolve_tp(model, mesh, sharding, tp)
        self._quant_kv = bool(getattr(caches[0], "quantized", False))
        self._wq = weight_qparams
        self._q8_gather = bool(quant_collectives)
        _ensure_quant_specs(self._tp, weight_qparams)
        # capture the param TENSORS once: per-step we only read their
        # current values, no module-tree walk in the serving hot loop
        self._param_tensors = dict(model.state_dict())
        self._fn = None
        # incremented inside the traced body: one bump per (re)trace, so
        # tests can assert the decode step compiles exactly once across
        # admission/eviction churn
        self.compile_count = 0

    def collective_bytes(self, slots: int):
        """Per-chip collective payload of one sharded decode step over
        ``slots`` slots ({} when single-chip)."""
        if self._tp is None:
            return {}
        return self._tp.collective_bytes(self.cfg, slots, slots,
                                         quant_gather=self._q8_gather)

    def _build(self):
        from ..autograd.tape import no_grad
        from ..ops.paged_attention import (_paged_attention_pallas,
                                           _paged_attention_xla,
                                           write_decode_kv,
                                           write_decode_kv_q8)
        from ..ops.pallas_kernels import (rope_qkv_epilogue,
                                          rope_tables_for_positions)
        model = self.model
        cfg = self.cfg
        llama = _inner_model(model)
        tp = self._tp
        deg = tp.degree if tp is not None else 1
        H = cfg.num_attention_heads // deg      # this chip's head shard
        Hkv = cfg.num_key_value_heads // deg
        D = cfg.hidden_size // cfg.num_attention_heads
        scale = 1.0 / math.sqrt(D)
        attn_fn = _paged_attention_pallas if self.use_pallas \
            else _paged_attention_xla
        quant_kv = self._quant_kv
        q8_gather = self._q8_gather
        pdtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        cp_axis = tp.cp_axis if tp is not None else None
        cp_deg = tp.cp_degree if tp is not None else 1
        if cp_deg > 1:
            from ..ops.online_softmax import cross_chip_merge
            from ..ops.paged_attention import (
                _paged_attention_xla_partial, write_ragged_kv)
            sink = self.caches[0].sink
            if sink < 0:
                raise ValueError(
                    "context-parallel DecodeStep needs a sink page "
                    "(PagedKVCache(sink_block=True)) to absorb the "
                    "stripe writes the chip does not own")

        sampling = self.sampling
        if sampling:
            from ..ops.sampling import sample_logits

        def step(params, tokens, seq_lens, block_tables, samp, kcs, vcs,
                 kss, vss):
            self.compile_count += 1
            S = tokens.shape[0]
            params = _materialize_params(params, pdtype)
            new_kcs, new_vcs = [], []
            new_kss, new_vss = [], []
            with model.bind_state(params), no_grad():
                x = _embed(llama, tokens[:, None], tp)        # [S, 1, h]
                if cfg.dtype == "bfloat16":
                    x = x.astype("bfloat16")
                # one-token-per-slot rows: positions = seq_lens; rope
                # tables built once per step, shared by every layer
                cos_t, sin_t = rope_tables_for_positions(
                    seq_lens, D, cfg.rope_theta)
                for li, (layer, kc, vc) in enumerate(
                        zip(llama.layers, kcs, vcs)):
                    h = layer.input_layernorm(x)
                    attn = layer.self_attn
                    q = attn.q_proj(h).reshape([S, 1, H, D])
                    k = attn.k_proj(h).reshape([S, 1, Hkv, D])
                    v = attn.v_proj(h).reshape([S, 1, Hkv, D])
                    qv, kv_, k_amax, v_amax = rope_qkv_epilogue(
                        q._value[:, 0], k._value[:, 0], v._value[:, 0],
                        cos_t, sin_t, with_amax=quant_kv)
                    if quant_kv:
                        kc, vc, ks, vs = write_decode_kv_q8(
                            kv_, v._value[:, 0], kc, vc,
                            kss[li], vss[li], block_tables, seq_lens,
                            k_amax=k_amax, v_amax=v_amax)
                        new_kss.append(ks)
                        new_vss.append(vs)
                    else:
                        ks = vs = None
                        if cp_deg > 1:
                            # global destination (block table at the
                            # GLOBAL block size), then stripe-local
                            # translation + the plain ragged scatter
                            bsl = kc.shape[1]
                            gbs = bsl * cp_deg
                            blk_g = jnp.take_along_axis(
                                block_tables,
                                (seq_lens // gbs)[:, None],
                                axis=1)[:, 0]
                            blk, off = _cp_local_dest(
                                blk_g, seq_lens % gbs, bsl, cp_axis,
                                sink)
                            kc, vc = write_ragged_kv(
                                kv_, v._value[:, 0], kc, vc, blk, off)
                        else:
                            kc, vc = write_decode_kv(
                                kv_, v._value[:, 0], kc, vc,
                                block_tables, seq_lens)
                    new_kcs.append(kc)
                    new_vcs.append(vc)
                    if cp_deg > 1:
                        bsl = kc.shape[1]
                        stripe = jax.lax.axis_index(cp_axis) * bsl
                        o_p, m_p, l_p = _paged_attention_xla_partial(
                            qv, kc, vc, block_tables, seq_lens + 1,
                            scale, stripe, bsl * cp_deg)
                        out = cross_chip_merge(o_p, m_p, l_p, cp_axis)
                    else:
                        out = attn_fn(qv, kc, vc, block_tables,
                                      seq_lens + 1, scale,
                                      key_scale=ks, value_scale=vs)
                    out = Tensor._from_value(out.reshape(S, 1, H * D))
                    x = x + _tp_psum(attn.o_proj(out), tp)
                    h2 = layer.post_attention_layernorm(x)
                    x = x + _ffn(layer, h2, tp)
                x = llama.norm(x)
                if model.lm_head is None:
                    from ..ops.linalg import matmul
                    logits = matmul(x, llama.embed_tokens.weight,
                                    transpose_y=True)
                else:
                    logits = model.lm_head(x)
                logits = _tp_logits(logits, tp, q8=q8_gather)
            # sampling ON DEVICE: only the [S] token ids cross the
            # link, never the [S, V] logits.  samp=None is the greedy
            # default path — the exact argmax, trace unchanged.
            if samp is None:
                nxt = jnp.argmax(
                    logits._value[:, 0, :].astype(jnp.float32),
                    axis=-1).astype(jnp.int32)
            else:
                t, k, p, sd = _samp_knobs(samp)
                # counter = the sampled token's global position
                nxt = sample_logits(logits._value[:, 0, :], t, k, p,
                                       sd, seq_lens + 1)
            return (nxt, tuple(new_kcs), tuple(new_vcs),
                    tuple(new_kss), tuple(new_vss))

        if sampling:
            fn, donate, n_repl = step, (5, 6, 7, 8), 4
        else:
            # greedy default: same operand pytree (and therefore the
            # same compiled module) as the pre-sampling step
            def fn(params, tokens, seq_lens, block_tables, kcs, vcs,
                   kss, vss):
                return step(params, tokens, seq_lens, block_tables,
                            None, kcs, vcs, kss, vss)
            donate, n_repl = (4, 5, 6, 7), 3
        if tp is None:
            self._fn = jax.jit(fn, donate_argnums=donate)
        else:
            self._fn = _wrap_sharded(fn, tp,
                                     self._wq or self._param_tensors,
                                     len(self.caches), n_repl=n_repl,
                                     donate=donate,
                                     quant_kv=quant_kv)

    def aot_lower(self, slots: int):
        """AOT-lower (never execute) the decode module at ``slots``
        slots with zero host operands — the graftlint hlo-contract
        artifact (donation aliases the pools, no f64, the split-step
        host-operand count stays pinned at 3)."""
        if self._fn is None:
            self._build()
        W = self.caches[0].num_blocks      # any width works for lint
        params = _step_params(self._param_tensors, self._tp, self._wq)
        kcs = tuple(c.key_cache for c in self.caches)
        vcs = tuple(c.value_cache for c in self.caches)
        kss, vss = _cache_scales(self.caches, self._quant_kv)
        args = [params,
                jnp.zeros((slots,), jnp.int32),
                jnp.zeros((slots,), jnp.int32),
                jnp.zeros((slots, W), jnp.int32)]
        if self.sampling:
            args.append(jnp.zeros((slots, 4), jnp.int32))
        return self._fn.lower(*args, kcs, vcs, kss, vss)

    def compiled_stats(self, slots: int) -> dict:
        """Cached ``cost_analysis`` of the compiled decode launch at
        ``slots`` slots (one token per slot per launch — see
        :func:`compiled_cost_stats`)."""
        cache = getattr(self, "_cost_stats", None)
        if cache is None:
            cache = self._cost_stats = {}
        if slots not in cache:
            cache[slots] = compiled_cost_stats(self.aot_lower(slots),
                                               slots)
        return cache[slots]

    def __call__(self, tokens, seq_lens, block_tables,
                 samp=None) -> np.ndarray:
        """samp (sampling steps only): [slots, 4] int32 per-slot knobs
        — (temperature bits, top_k, top_p bits, seed)."""
        if self._fn is None:
            self._build()
        params = _step_params(self._param_tensors, self._tp, self._wq)
        kcs = tuple(c.key_cache for c in self.caches)
        vcs = tuple(c.value_cache for c in self.caches)
        kss, vss = _cache_scales(self.caches, self._quant_kv)
        args = [params,
                jnp.asarray(np.asarray(tokens, np.int32)),
                jnp.asarray(np.asarray(seq_lens, np.int32)),
                jnp.asarray(np.asarray(block_tables, np.int32))]
        if self.sampling:
            if samp is None:
                raise ValueError(
                    "sampling DecodeStep needs the per-slot knob array "
                    "(engine fills it; greedy slots are temperature 0)")
            args.append(jnp.asarray(np.asarray(samp, np.int32)))
        nxt, new_kcs, new_vcs, new_kss, new_vss = self._fn(
            *args, kcs, vcs, kss, vss)
        _rebind_caches(self.caches, new_kcs, new_vcs, new_kss, new_vss)
        return np.asarray(nxt)
