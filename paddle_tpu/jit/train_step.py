"""Fully-fused compiled training step.

The TPU-native execution form of SURVEY.md §3.5: forward + backward +
optimizer update traced into ONE XLA module (loss scaling / grad clip
included), with buffer donation so parameters update in place in HBM.
This is what bench.py and __graft_entry__ run; the eager tape remains the
flexible path.

Usage:
    step = TrainStep(model, criterion, optimizer)
    loss = step(batch_inputs, labels)        # one fused XLA call
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer, Parameter
from ..optimizer.optimizer import Optimizer
from ..ops import random as _random


class TrainStep:
    """Compile model+criterion+optimizer into one donated-buffer XLA step."""

    def __init__(self, model: Layer, criterion: Callable,
                 optimizer: Optimizer, clip_norm: Optional[float] = None):
        self.model = model
        self.criterion = criterion
        self.optimizer = optimizer
        self.clip_norm = clip_norm

        sd = model.state_dict()
        self._keys = list(sd.keys())
        self._trainable = [k for k in self._keys
                           if isinstance(sd[k], Parameter)
                           and not sd[k].stop_gradient]
        self._frozen = [k for k in self._keys if k not in self._trainable]
        # optimizer state pytree per trainable param
        self._opt_states = {k: optimizer._ensure_state(sd[k])
                            for k in self._trainable}
        self._step_fn = None

    def _build(self):
        model = self.model
        criterion = self.criterion
        opt = self.optimizer
        trainable = self._trainable
        frozen = self._frozen
        clip_norm = self.clip_norm

        def step(params, frozen_vals, opt_states, lr, key, *batch):
            def loss_fn(p):
                state = dict(p)
                state.update(frozen_vals)
                with model.bind_state(state):
                    with _random.trace_rng_scope(key):
                        out = model(*[Tensor._from_value(b)
                                      for b in batch[:-1]])
                        loss = criterion(out,
                                         Tensor._from_value(batch[-1]))
                    # collect traced buffer updates (BatchNorm running
                    # stats reassign their bound tracer in training
                    # mode — F.batch_norm's contract expects the fused
                    # step to persist them) BEFORE bind_state restores
                    # the originals.  Returned as aux: excluded from
                    # the grad but part of the compiled step's outputs.
                    new_bufs = {}
                    sd = model.state_dict()
                    for k in frozen:
                        v = sd[k]._value
                        if v is not state[k]:
                            new_bufs[k] = v
                return loss._value.astype(jnp.float32), new_bufs

            (loss, new_bufs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)

            if clip_norm is not None:
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in grads.values()))
                scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                grads = {k: (g * scale).astype(g.dtype)
                         for k, g in grads.items()}

            hyper = {"lr": lr}
            new_params = {}
            new_states = {}
            for k in trainable:
                np_, nst = opt._update_rule(params[k], grads[k],
                                            opt_states[k], hyper)
                new_params[k] = np_
                new_states[k] = nst
            return loss, new_params, new_states, new_bufs

        # donate params + opt states: in-place HBM update
        self._step_fn = jax.jit(step, donate_argnums=(0, 2))

    def lower(self, *batch):
        """AOT-lower the fused step with the current params/shardings
        (used by DistModel.dist_main_program and the dist-attr
        read-back)."""
        if self._step_fn is None:
            self._build()
        sd = self.model.state_dict()
        params = {k: sd[k]._value for k in self._trainable}
        frozen_vals = {k: sd[k]._value for k in self._frozen}
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        # fixed dummy key: lowering must not perturb the training RNG
        # stream (the key value cannot affect the lowered HLO)
        key = jax.random.PRNGKey(0)
        batch_vals = tuple(b._value if isinstance(b, Tensor)
                           else jnp.asarray(b) for b in batch)
        return self._step_fn.lower(params, frozen_vals, self._opt_states,
                                   lr, key, *batch_vals)

    def __call__(self, *batch):
        if self._step_fn is None:
            self._build()
        sd = self.model.state_dict()
        params = {k: sd[k]._value for k in self._trainable}
        frozen_vals = {k: sd[k]._value for k in self._frozen}
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.next_key()
        batch_vals = tuple(b._value if isinstance(b, Tensor)
                           else jnp.asarray(b) for b in batch)
        loss, new_params, new_states, new_bufs = self._step_fn(
            params, frozen_vals, self._opt_states, lr, key, *batch_vals)
        for k, v in new_params.items():
            sd[k]._value = v
        # persist traced buffer updates (BatchNorm running stats)
        for k, v in new_bufs.items():
            sd[k]._value = v
        # update the per-param state DICTS in place: optimizer._state
        # holds the same dict objects, so optimizer.state_dict() stays
        # valid after the donated buffers die
        for k, nst in new_states.items():
            self._opt_states[k].update(nst)
        if isinstance(self.optimizer._learning_rate, object) and \
                hasattr(self.optimizer._learning_rate, "step"):
            pass  # caller drives the scheduler
        self.optimizer._global_step += 1
        return Tensor._from_value(loss)
