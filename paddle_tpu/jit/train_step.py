"""Fully-fused compiled training step.

The TPU-native execution form of SURVEY.md §3.5: forward + backward +
optimizer update traced into ONE XLA module (loss scaling / grad clip
included), with buffer donation so parameters update in place in HBM.
This is what bench.py and __graft_entry__ run; the eager tape remains the
flexible path.

Usage:
    step = TrainStep(model, criterion, optimizer)
    loss = step(batch_inputs, labels)        # one fused XLA call

ZeRO-1/2 sharded weight update (Xu et al., arXiv:2004.13336 "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training"):
pass a mesh + :class:`ShardingConfig` and the SAME donated module
reduce-scatters gradients over the data-parallel axis, applies the
optimizer update to only this replica's 1/dp shard of the parameters and
optimizer state (states are CREATED sharded via ``NamedSharding`` —
never materialized replicated), then all-gathers the updated parameters:

    cfg  = ShardingConfig(stage=2)           # 1 = os, 2 = os_g (ZeRO-2)
    step = TrainStep(model, criterion, opt, mesh=mesh, sharding=cfg)

Optimizer-state HBM per replica drops by the dp degree; stage-2 lowers
the grad sync itself to ONE ``reduce-scatter`` per coalesced bucket
(the same dtype-bucketed flat-buffer layout as the DP-overlap
``coalesce_tensor`` machinery in ``distributed/passes``), instead of a
full-gradient all-reduce.  The sharded step is an explicit SPMD program
(``shard_map``): each replica computes grads on its batch shard, so the
criterion must be batch-separable with a mean (default) or sum
reduction — the standard data-parallel contract.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer, Parameter
from ..optimizer.optimizer import Optimizer
from ..ops import random as _random
# the mesh/axis/spec machinery is shared with the serving steps — one
# SPMD module (jit/spmd.py) is the single source of both; ShardingConfig
# is re-exported here for the existing import sites
from .spmd import (ShardingConfig, SpecLayout, _entry_names,
                   gather_spec_axes, llama_param_specs,
                   resolve_mesh_axis, spec_axes)

__all__ = ["TrainStep", "ShardingConfig"]


class _ParamShim:
    """Duck-typed stand-in so ``optimizer._init_state`` can be traced
    (it only reads ``p._value`` and ``p.name``)."""

    def __init__(self, value, name):
        self._value = value
        self.name = name


class TrainStep:
    """Compile model+criterion+optimizer into one donated-buffer XLA step."""

    def __init__(self, model: Layer, criterion: Callable,
                 optimizer: Optimizer, clip_norm: Optional[float] = None,
                 mesh=None, sharding: Optional[ShardingConfig] = None):
        self.model = model
        self.criterion = criterion
        self.optimizer = optimizer
        self.clip_norm = clip_norm
        # bumped inside the traced body: one bump per (re)trace, so tests
        # can assert the step compiles exactly once across training
        self.compile_count = 0

        sd = model.state_dict()
        self._keys = list(sd.keys())
        self._trainable = [k for k in self._keys
                           if isinstance(sd[k], Parameter)
                           and not sd[k].stop_gradient]
        self._frozen = [k for k in self._keys if k not in self._trainable]
        self._step_fn = None

        # a sharding pass / group_sharded_parallel may have marked the
        # optimizer for the fused sharded path — pick it up so the eager
        # wrapper and the compiled path agree.  An implicit marker must
        # never make a previously-working construction crash: it degrades
        # to the replicated step with a warning instead of raising.
        implicit = False
        if mesh is None and sharding is None:
            marker = getattr(optimizer, "_sharded_update", None)
            if marker is not None:
                mesh, sharding = marker
                implicit = True

        self._sharded = False
        if mesh is not None or sharding is not None:
            try:
                self._setup_sharded(mesh, sharding or ShardingConfig(), sd)
            except (ValueError, NotImplementedError):
                if not implicit:
                    raise
                import warnings
                import sys as _sys
                warnings.warn(
                    f"ignoring the optimizer's _sharded_update marker "
                    f"({_sys.exc_info()[1]}); building the replicated "
                    f"TrainStep instead", stacklevel=2)
                self._sharded = False

        if not self._sharded:
            # optimizer state pytree per trainable param (replicated path)
            self._opt_states = {k: optimizer._ensure_state(sd[k])
                                for k in self._trainable}

    # -- sharded setup -------------------------------------------------------
    def _setup_sharded(self, mesh, cfg: ShardingConfig, sd):
        # 2D (fsdp×tp) mesh (round 21): params/grads/optimizer state
        # live fsdp×tp-sharded end to end — ZeRO-3 as the storage
        # layout, composed with the serving tp placement
        if mesh is not None:
            from ..distributed.process_mesh import as_jax_mesh
            probe = as_jax_mesh(mesh)
            total = 1
            for a in probe.axis_names:
                total *= probe.shape[a]
            # any mesh that names an fsdp axis and has >1 chip takes
            # the 2D path — including fsdp=1 x tp>1, where tp alone is
            # the storage axis (a degenerate-but-valid grid corner)
            if "fsdp" in probe.axis_names and total > 1:
                self._setup_sharded_2d(probe, cfg, sd)
                return
        jmesh, axis, deg = resolve_mesh_axis(
            mesh, cfg.axis, cfg.degree,
            candidates=("dp", "sharding", "data"))
        if deg <= 1:
            return     # degenerate: plain replicated step
        other = [a for a in jmesh.axis_names if a != axis
                 and jmesh.shape[a] > 1]
        if other:
            raise NotImplementedError(
                f"the 1D sharded weight update composes only with pure "
                f"data parallelism; mesh has extra axes {other} — for "
                f"fsdp×tp weight sharding name the storage axis 'fsdp' "
                f"(spmd.mesh_2d) and the 2D path takes over")
        if not getattr(self.optimizer, "shardable_update", True):
            raise ValueError(
                f"{type(self.optimizer).__name__}'s update rule is not "
                f"elementwise (cross-element reductions would be computed "
                f"per shard) — use the replicated TrainStep; its state is "
                f"small anyway")
        self._sharded = True
        self._mode = "1d"
        self._jmesh = jmesh
        self._axis = axis
        self._deg = deg
        self._shard_cfg = cfg
        from jax.sharding import NamedSharding, PartitionSpec
        self._repl = NamedSharding(jmesh, PartitionSpec())
        self._row_sh = NamedSharding(jmesh, PartitionSpec(axis))

        # which params can shard their update: dim0 divisible by the
        # degree AND every array state leaf is param-shaped (elementwise
        # state) — others update replicated on every rank
        self._shardable: Dict[str, bool] = {}
        self._state_shardings: Dict[str, Dict[str, Any]] = {}
        for k in self._trainable:
            p = sd[k]
            shape = tuple(p._value.shape)
            ok = len(shape) >= 1 and shape[0] % deg == 0
            if ok:
                abstract = jax.eval_shape(
                    self._make_state_init(p, k),
                    jax.ShapeDtypeStruct(shape, p._value.dtype))
                for leaf in jax.tree_util.tree_leaves(abstract):
                    if leaf.ndim >= 1 and tuple(leaf.shape) != shape:
                        import warnings
                        warnings.warn(
                            f"param {k!r}: optimizer state leaf of shape "
                            f"{leaf.shape} is not parameter-shaped; its "
                            f"update stays replicated", stacklevel=3)
                        ok = False
                        break
            self._shardable[k] = ok
        self._opt_states = {}
        for k in self._trainable:
            self._refresh_state(k, sd[k])

    def _setup_sharded_2d(self, jmesh, cfg: ShardingConfig, sd):
        """fsdp×tp weight sharding (round 21): every trainable param is
        STORED in its composed family placement (``spmd.SpecLayout``
        with an fsdp axis — ZeRO-3 subsumed as the storage layout, no
        stage knob), optimizer state and grads inherit it, and the
        traced step gathers for compute / reduce-scatters back.  Extra
        mesh axes (a ``dp`` replica axis) are pure batch parallelism:
        the batch shards over EVERY axis and grads reduce over the
        axes a spec does not name."""
        if not getattr(self.optimizer, "shardable_update", True):
            raise ValueError(
                f"{type(self.optimizer).__name__}'s update rule is not "
                f"elementwise (cross-element reductions would be computed "
                f"per shard) — use the replicated TrainStep; its state is "
                f"small anyway")
        from jax.sharding import NamedSharding, PartitionSpec
        self._sharded = True
        self._mode = "2d"
        self._jmesh = jmesh
        self._shard_cfg = cfg
        sizes = dict(jmesh.shape)
        self._axes = tuple(jmesh.axis_names)
        self._deg = 1
        for a in self._axes:
            self._deg *= sizes[a]
        tp_live = sizes.get("tp", 1) > 1
        self._fsdp_deg = sizes["fsdp"]
        self._tp_deg = sizes.get("tp", 1)
        self._repl = NamedSharding(jmesh, PartitionSpec())
        self._row_sh = None              # 1D-path artifact, unused here
        layout = SpecLayout(tp_axis="tp" if tp_live else None,
                            fsdp_axis="fsdp")
        shapes = {k: tuple(sd[k]._value.shape) for k in self._trainable}
        specs = llama_param_specs(self._trainable, layout,
                                  shapes=shapes, mesh=jmesh)
        # shardability: a named spec AND param-shaped (elementwise)
        # optimizer state — a non-param-shaped leaf forces the whole
        # param back to replicated, same contract as the 1D path
        self._shardable: Dict[str, bool] = {}
        self._param_specs: Dict[str, Any] = {}
        self._param_sh: Dict[str, Any] = {}
        self._state_shardings: Dict[str, Dict[str, Any]] = {}
        for k in self._trainable:
            p = sd[k]
            spec = specs[k]
            ok = bool(spec_axes(spec))
            if ok:
                abstract = jax.eval_shape(
                    self._make_state_init(p, k),
                    jax.ShapeDtypeStruct(shapes[k], p._value.dtype))
                for leaf in jax.tree_util.tree_leaves(abstract):
                    if leaf.ndim >= 1 and tuple(leaf.shape) != shapes[k]:
                        import warnings
                        warnings.warn(
                            f"param {k!r}: optimizer state leaf of shape "
                            f"{leaf.shape} is not parameter-shaped; its "
                            f"param stays replicated", stacklevel=3)
                        ok = False
                        break
            if not ok:
                spec = PartitionSpec()
            self._shardable[k] = ok
            self._param_specs[k] = spec
            self._param_sh[k] = NamedSharding(jmesh, spec)
        self._opt_states = {}
        for k in self._trainable:
            self._refresh_state(k, sd[k])
        # observability: the storage-sharding degree this process
        # trains at, plus the static per-dispatch fsdp/tp param-gather
        # payload (counted per step in __call__)
        from ..observability import default_registry
        r = default_registry()
        r.gauge(
            "train_fsdp_degree",
            "fsdp (weight-storage sharding) degree of the most "
            "recently constructed 2D TrainStep in this process "
            "(1 = params replicated)").set(self._fsdp_deg)
        self._m_gather_bytes = r.counter(
            "spmd_allgather_bytes_total",
            "per-chip bytes received by spmd param all-gathers, by "
            "site: the 2D train step's per-step param gather "
            "(train_params) and the sharded serving prologue's fsdp "
            "gather (serving_params)", labels=("site",)
        ).labels(site="train_params")
        self._gather_bytes_per_step = 0
        for k in self._trainable:
            part = 1
            for name in spec_axes(self._param_specs[k]):
                part *= sizes.get(name, 1)
            if part > 1:
                v = sd[k]._value
                nbytes = int(np.prod(shapes[k])) * v.dtype.itemsize
                self._gather_bytes_per_step += \
                    nbytes - nbytes // part

    def _make_state_init(self, p, k):
        opt = self.optimizer
        name = getattr(p, "name", k)
        multi = bool(getattr(opt, "_multi_precision", False))

        def init_fn(pv):
            st = opt._init_state(_ParamShim(pv, name))
            if multi and pv.dtype in (jnp.bfloat16, jnp.float16):
                st["master"] = pv.astype(jnp.float32)
            return st

        return init_fn

    def _leaf_sharding(self, k, p, leaf_shape):
        if self._shardable[k] and len(leaf_shape) >= 1 \
                and tuple(leaf_shape) == tuple(p._value.shape):
            return self._param_sh[k] if getattr(self, "_mode", "1d") \
                == "2d" else self._row_sh
        return self._repl

    def _refresh_state(self, k, p):
        """Bind ``self._opt_states[k]`` to the optimizer's live state dict
        for ``p``, creating it ALREADY SHARDED (jitted init with
        ``out_shardings`` — the replicated tensor never exists) or
        re-placing leaves that lost their sharding (set_state_dict loads
        full host arrays)."""
        opt_state = self.optimizer._state
        st = opt_state.get(id(p))
        if st is not None and st is self._opt_states.get(k):
            # fast path for the hot loop: the step updates this dict in
            # place with already-sharded outputs, so nothing to re-place
            # unless set_state_dict swapped the dict object out
            return
        if st is None:
            init_fn = self._make_state_init(p, k)
            abstract = jax.eval_shape(
                init_fn, jax.ShapeDtypeStruct(p._value.shape,
                                              p._value.dtype))
            out_sh = jax.tree_util.tree_map(
                lambda l: self._leaf_sharding(k, p, l.shape), abstract)
            st = jax.jit(init_fn, out_shardings=out_sh)(p._value)
            opt_state[id(p)] = st
        shardings = {}
        for name, v in st.items():
            if not hasattr(v, "shape"):
                continue
            sh = self._leaf_sharding(k, p, v.shape)
            shardings[name] = sh
            if not (isinstance(v, jax.Array) and v.sharding == sh):
                st[name] = jax.device_put(jnp.asarray(v), sh)
        self._opt_states[k] = st
        self._state_shardings[k] = shardings

    def _place_replicated(self, sd):
        """Params + frozen buffers replicated over the mesh before the
        call, so jit never reshards a donated argument (donation aliases
        from the very first step)."""
        for k in self._trainable + self._frozen:
            v = sd[k]._value
            if not (isinstance(v, jax.Array) and v.sharding == self._repl):
                sd[k]._value = jax.device_put(jnp.asarray(v), self._repl)

    def _place_params_2d(self, sd):
        """2D path: trainable params placed in their fsdp×tp STORAGE
        sharding (the replicated tensor never exists past the first
        placement — ZeRO-3), frozen buffers replicated.  Arrays already
        carrying their sharding (the step's own outputs, or a serving
        tree handed back) are left untouched, so steady state pays an
        equality probe, never a transfer."""
        for k in self._trainable:
            v = sd[k]._value
            sh = self._param_sh[k]
            if not (isinstance(v, jax.Array) and v.sharding == sh):
                sd[k]._value = jax.device_put(jnp.asarray(v), sh)
        for k in self._frozen:
            v = sd[k]._value
            if not (isinstance(v, jax.Array) and v.sharding == self._repl):
                sd[k]._value = jax.device_put(jnp.asarray(v), self._repl)

    # -- traced loss (shared by both paths) ----------------------------------
    def _make_loss_fn(self, frozen_vals, batch, key):
        model, criterion, frozen = self.model, self.criterion, self._frozen

        def loss_fn(p):
            state = dict(p)
            state.update(frozen_vals)
            with model.bind_state(state):
                with _random.trace_rng_scope(key):
                    out = model(*[Tensor._from_value(b)
                                  for b in batch[:-1]])
                    loss = criterion(out,
                                     Tensor._from_value(batch[-1]))
                # collect traced buffer updates (BatchNorm running
                # stats reassign their bound tracer in training
                # mode — F.batch_norm's contract expects the fused
                # step to persist them) BEFORE bind_state restores
                # the originals.  Returned as aux: excluded from
                # the grad but part of the compiled step's outputs.
                new_bufs = {}
                sd = model.state_dict()
                for k in frozen:
                    v = sd[k]._value
                    if v is not state[k]:
                        new_bufs[k] = v
            return loss._value.astype(jnp.float32), new_bufs

        return loss_fn

    # -- replicated build -----------------------------------------------------
    def _build(self):
        opt = self.optimizer
        trainable = self._trainable
        clip_norm = self.clip_norm

        def step(params, frozen_vals, opt_states, lr, key, *batch):
            self.compile_count += 1
            loss_fn = self._make_loss_fn(frozen_vals, batch, key)
            (loss, new_bufs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)

            if clip_norm is not None:
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in grads.values()))
                scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                grads = {k: (g * scale).astype(g.dtype)
                         for k, g in grads.items()}

            hyper = {"lr": lr}
            new_params = {}
            new_states = {}
            for k in trainable:
                np_, nst = opt._update_rule(params[k], grads[k],
                                            opt_states[k], hyper)
                new_params[k] = np_
                new_states[k] = nst
            return loss, new_params, new_states, new_bufs

        # donate params + opt states: in-place HBM update
        self._step_fn = jax.jit(step, donate_argnums=(0, 2))

    # -- sharded build --------------------------------------------------------
    def _grad_buckets(self):
        """Stage-2 coalesce layout: shardable keys grouped by dtype, then
        packed into buckets of <= bucket_mb — ONE reduce-scatter per
        bucket over the flat (degree, cols) buffer (the coalesce_tensor
        fused-buffer idea applied to the grad sync)."""
        sd = self.model.state_dict()
        budget = int(self._shard_cfg.bucket_mb * 1024 * 1024)
        groups: Dict[str, List[str]] = {}
        nonshard = []
        for k in self._trainable:
            if self._shardable[k]:
                groups.setdefault(str(sd[k]._value.dtype), []).append(k)
            else:
                nonshard.append(k)
        buckets: List[List[str]] = []
        for keys in groups.values():
            cur, cur_bytes = [], 0
            for k in keys:
                v = sd[k]._value
                nbytes = int(np.prod(v.shape)) * v.dtype.itemsize
                if cur and cur_bytes + nbytes > budget:
                    buckets.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(k)
                cur_bytes += nbytes
            if cur:
                buckets.append(cur)
        return buckets, nonshard

    def _build_sharded(self, batch_vals):
        from ..core.jax_compat import shard_map_compat
        from jax.sharding import NamedSharding, PartitionSpec

        opt = self.optimizer
        trainable, frozen = self._trainable, self._frozen
        clip_norm = self.clip_norm
        mesh, axis, deg = self._jmesh, self._axis, self._deg
        cfg = self._shard_cfg
        stage = cfg.stage
        mean_combine = cfg.loss_reduction == "mean"
        shardable = self._shardable
        buckets, nonshard = self._grad_buckets()
        sd0 = self.model.state_dict()
        shapes = {k: tuple(sd0[k]._value.shape) for k in trainable}
        rows = {k: shapes[k][0] // deg for k in trainable if shardable[k]}

        def sync_grads(grads):
            """All grads leave this function mean/sum-combined across
            replicas; shardable keys leave SHARDED (this rank's rows)."""
            out = {}
            for bucket in buckets:
                cols = [int(np.prod(shapes[k])) // deg for k in bucket]
                mat = jnp.concatenate(
                    [grads[k].reshape(deg, -1) for k in bucket], axis=1) \
                    if len(bucket) > 1 else grads[bucket[0]].reshape(deg, -1)
                if stage >= 2:
                    # ZeRO-2: each rank only ever receives its grad shard
                    row = jax.lax.psum_scatter(mat, axis,
                                               scatter_dimension=0,
                                               tiled=False)
                else:
                    # ZeRO-1: full-gradient all-reduce, local row slice
                    full = jax.lax.psum(mat, axis)
                    row = jnp.squeeze(jax.lax.dynamic_slice_in_dim(
                        full, jax.lax.axis_index(axis), 1, 0), 0)
                if mean_combine:
                    row = row / deg
                off = 0
                for k, c in zip(bucket, cols):
                    out[k] = row[off:off + c].reshape(
                        (rows[k],) + shapes[k][1:])
                    off += c
            # non-shardable params: coalesced all-reduce, replicated update
            by_dtype: Dict[str, List[str]] = {}
            for k in nonshard:
                by_dtype.setdefault(str(grads[k].dtype), []).append(k)
            for keys in by_dtype.values():
                flat = jnp.concatenate([grads[k].reshape(-1)
                                        for k in keys]) \
                    if len(keys) > 1 else grads[keys[0]].reshape(-1)
                red = jax.lax.psum(flat, axis)
                if mean_combine:
                    red = red / deg
                off = 0
                for k in keys:
                    n = int(np.prod(shapes[k])) if shapes[k] else 1
                    out[k] = red[off:off + n].reshape(shapes[k])
                    off += n
            return out

        def step(params, frozen_vals, opt_states, lr, key, *batch):
            self.compile_count += 1
            idx = jax.lax.axis_index(axis)
            # distinct dropout stream per replica (true-DP semantics)
            loss_fn = self._make_loss_fn(
                frozen_vals, batch, jax.random.fold_in(key, idx))
            (loss, new_bufs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)

            grads = sync_grads(grads)

            if clip_norm is not None:
                # global grad norm: sharded pieces psum'd, replicated
                # pieces counted once per rank (identical on all ranks)
                local = sum((jnp.sum(jnp.square(
                    grads[k].astype(jnp.float32)))
                    for k in trainable if shardable[k]),
                    jnp.asarray(0.0, jnp.float32))
                total = jax.lax.psum(local, axis) + sum(
                    (jnp.sum(jnp.square(grads[k].astype(jnp.float32)))
                     for k in trainable if not shardable[k]),
                    jnp.asarray(0.0, jnp.float32))
                gnorm = jnp.sqrt(total)
                scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                grads = {k: (g * scale).astype(g.dtype)
                         for k, g in grads.items()}

            hyper = {"lr": lr}
            new_params = {}
            new_states = {}
            for k in trainable:
                if shardable[k]:
                    # update THIS rank's 1/deg rows, then all-gather the
                    # refreshed parameter (the weight-update-sharding
                    # dataflow of arXiv:2004.13336)
                    p_sh = jax.lax.dynamic_slice_in_dim(
                        params[k], idx * rows[k], rows[k], 0)
                    np_, nst = opt._update_rule(p_sh, grads[k],
                                                opt_states[k], hyper)
                    new_params[k] = jax.lax.all_gather(
                        np_, axis, axis=0, tiled=True)
                else:
                    np_, nst = opt._update_rule(params[k], grads[k],
                                                opt_states[k], hyper)
                    new_params[k] = np_
                new_states[k] = nst
            # combine per-replica losses the same way the grads combine,
            # so the reported loss matches the replicated step's
            loss = jax.lax.pmean(loss, axis) if mean_combine \
                else jax.lax.psum(loss, axis)
            # running stats (BN) are averages in either mode
            new_bufs = jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, axis), new_bufs)
            return loss, new_params, new_states, new_bufs

        P = PartitionSpec
        repl_spec = P()
        state_specs = {
            k: {n: (P(axis) if sh is self._row_sh else P())
                for n, sh in self._state_shardings[k].items()}
            for k in trainable}
        batch_specs = tuple(P(axis) if np.ndim(b) >= 1 else P()
                            for b in batch_vals)
        in_specs = (repl_spec, repl_spec, state_specs, repl_spec,
                    repl_spec) + batch_specs
        out_specs = (repl_spec, repl_spec, state_specs, repl_spec)
        fn = shard_map_compat(step, mesh, in_specs=in_specs,
                              out_specs=out_specs)

        def to_sh(spec_tree):
            return jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), spec_tree,
                is_leaf=lambda s: isinstance(s, PartitionSpec))

        state_sh = to_sh(state_specs)
        in_sh = (self._repl, self._repl, state_sh, self._repl,
                 self._repl) + tuple(to_sh(s) for s in batch_specs)
        out_sh = (self._repl, self._repl, state_sh, self._repl)
        self._step_fn = jax.jit(fn, donate_argnums=(0, 2),
                                in_shardings=in_sh, out_shardings=out_sh)

    def _build_sharded_2d(self, batch_vals):
        """The fsdp×tp traced body (round 21).  Params enter (and
        leave) in their composed STORAGE placement; per step each param
        is all-gathered over every axis its spec names (the ZeRO-3
        gather — under a 2D mesh the tp axis too acts as a storage
        axis for training, since compute here is batch-parallel over
        ALL chips), grads reduce-scatter straight back into the
        placement (one ``psum_scatter`` per sharded dim, a plain
        ``psum`` over the axes the spec does not name), and the
        elementwise update runs on the local shard with local state —
        no trailing param all-gather, the output IS the placement the
        serving steps consume.  Donation (params + opt states) and the
        compile-count contract are unchanged from the 1D path."""
        from ..core.jax_compat import shard_map_compat
        from jax.sharding import NamedSharding, PartitionSpec

        opt = self.optimizer
        trainable = self._trainable
        clip_norm = self.clip_norm
        mesh, axes = self._jmesh, self._axes
        sizes = dict(mesh.shape)
        live_axes = tuple(a for a in axes if sizes[a] > 1)
        total = self._deg
        mean_combine = self._shard_cfg.loss_reduction == "mean"
        specs = self._param_specs

        def linear_index():
            idx = jnp.asarray(0, jnp.int32)
            for a in axes:
                idx = idx * sizes[a] + jax.lax.axis_index(a)
            return idx

        def sync_grads(grads):
            """Every grad leaves reduced over ALL mesh axes and
            scattered into its param's placement: psum_scatter along
            each spec-named dim (major-to-minor within a dim), psum
            over the remaining axes."""
            out = {}
            for k in trainable:
                g = grads[k]
                remaining = [a for a in live_axes]
                for dim, entry in enumerate(specs[k]):
                    for name in _entry_names(entry):
                        g = jax.lax.psum_scatter(
                            g, name, scatter_dimension=dim, tiled=True)
                        remaining.remove(name)
                if remaining:
                    g = jax.lax.psum(g, tuple(remaining))
                if mean_combine:
                    g = g / total
                out[k] = g
            return out

        def step(params, frozen_vals, opt_states, lr, key, *batch):
            self.compile_count += 1
            # the ZeRO-3 compute gather: full value per spec-named axis
            full = {k: gather_spec_axes(params[k], specs[k])
                    for k in trainable}
            # distinct dropout stream per chip — the linear (…,fsdp,tp)
            # index matches the 1D dp path's replica order, so an
            # fsdp×tp run draws the same per-shard streams as dp at
            # equal total degree (the parity gate relies on it)
            loss_fn = self._make_loss_fn(
                frozen_vals, batch, jax.random.fold_in(key,
                                                       linear_index()))
            (loss, new_bufs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(full)

            grads = sync_grads(grads)

            if clip_norm is not None:
                # global grad norm from the PLACED shards: per group of
                # params sharing a spec-axis set, sum local squares and
                # psum over exactly those axes (replicated contributions
                # count once; sharded ones sum to the full square norm)
                groups: Dict[tuple, Any] = {}
                for k in trainable:
                    ax = tuple(sorted(set(spec_axes(specs[k]))))
                    sq = jnp.sum(jnp.square(
                        grads[k].astype(jnp.float32)))
                    groups[ax] = groups.get(
                        ax, jnp.asarray(0.0, jnp.float32)) + sq
                tot = jnp.asarray(0.0, jnp.float32)
                for ax, sq in groups.items():
                    tot = tot + (jax.lax.psum(sq, ax) if ax else sq)
                gnorm = jnp.sqrt(tot)
                scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                grads = {k: (g * scale).astype(g.dtype)
                         for k, g in grads.items()}

            hyper = {"lr": lr}
            new_params = {}
            new_states = {}
            for k in trainable:
                # params, grads and state are ALL in the placement —
                # the elementwise update needs no slicing and no
                # trailing gather (arXiv:2004.13336 generalized to 2D)
                np_, nst = opt._update_rule(params[k], grads[k],
                                            opt_states[k], hyper)
                new_params[k] = np_
                new_states[k] = nst
            loss = jax.lax.pmean(loss, live_axes) if mean_combine \
                else jax.lax.psum(loss, live_axes)
            new_bufs = jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, live_axes), new_bufs)
            return loss, new_params, new_states, new_bufs

        P = PartitionSpec
        repl_spec = P()
        param_specs = {k: specs[k] for k in trainable}
        state_specs = {
            k: {n: sh.spec
                for n, sh in self._state_shardings[k].items()}
            for k in trainable}
        batch_specs = tuple(P(axes) if np.ndim(b) >= 1 else P()
                            for b in batch_vals)
        in_specs = (param_specs, repl_spec, state_specs, repl_spec,
                    repl_spec) + batch_specs
        out_specs = (repl_spec, param_specs, state_specs, repl_spec)
        fn = shard_map_compat(step, mesh, in_specs=in_specs,
                              out_specs=out_specs)

        def to_sh(spec_tree):
            return jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), spec_tree,
                is_leaf=lambda s: isinstance(s, PartitionSpec))

        in_sh = (to_sh(param_specs), self._repl, to_sh(state_specs),
                 self._repl, self._repl) + tuple(to_sh(s)
                                                 for s in batch_specs)
        out_sh = (self._repl, to_sh(param_specs), to_sh(state_specs),
                  self._repl)
        self._step_fn = jax.jit(fn, donate_argnums=(0, 2),
                                in_shardings=in_sh, out_shardings=out_sh)

    # -- checkpoint plumbing --------------------------------------------------
    # The CheckpointManager snapshots these LIVE (possibly ZeRO-sharded)
    # state arrays shard-wise at a step boundary; restore reshards them
    # onto whatever mesh/dp degree the resumed run is using.
    def opt_state_arrays(self) -> Dict[str, Any]:
        """Flat ``{"opt.<param>.<leaf>": array}`` of the live optimizer
        state — sharded leaves stay sharded (the manager saves each
        replica's shard with its global offset)."""
        out = {}
        for k in self._trainable:
            for name, v in self._opt_states[k].items():
                if hasattr(v, "shape"):
                    out[f"opt.{k}.{name}"] = v
        return out

    def load_opt_state_arrays(self, flat: Dict[str, Any]):
        """Restore state saved by :meth:`opt_state_arrays` — possibly
        under a DIFFERENT dp degree: each full (reassembled) array is
        ``device_put`` with THIS step's current sharding, which is the
        whole reshard path (array redistribution, arXiv:2112.01075).
        Unknown keys are ignored; missing keys keep their fresh init."""
        for k in self._trainable:
            st = self._opt_states[k]
            for name, cur in list(st.items()):
                full = flat.get(f"opt.{k}.{name}")
                if full is None or not hasattr(cur, "shape"):
                    continue
                val = jnp.asarray(np.asarray(full)).astype(cur.dtype)
                if tuple(val.shape) != tuple(cur.shape):
                    raise ValueError(
                        f"checkpointed state {k}.{name} has shape "
                        f"{val.shape}, current run expects {cur.shape}")
                if self._sharded:
                    sh = self._state_shardings[k].get(name)
                    if sh is not None:
                        val = jax.device_put(val, sh)
                # in-place: optimizer._state holds the same dict object
                st[name] = val

    @property
    def global_step(self) -> int:
        """Steps applied through this TrainStep (the optimizer's counter
        — restored by the checkpoint layer on resume)."""
        return int(self.optimizer._global_step)

    # -- common driver --------------------------------------------------------
    def _ensure_built(self, batch_vals):
        if self._step_fn is None:
            if self._sharded and getattr(self, "_mode", "1d") == "2d":
                self._build_sharded_2d(batch_vals)
            elif self._sharded:
                self._build_sharded(batch_vals)
            else:
                self._build()

    def _gather_inputs(self, batch):
        sd = self.model.state_dict()
        batch_vals = tuple(b._value if isinstance(b, Tensor)
                           else jnp.asarray(b) for b in batch)
        if self._sharded:
            for b in batch_vals:
                if np.ndim(b) >= 1 and b.shape[0] % self._deg:
                    # fail with an actionable message instead of the
                    # cryptic mid-jit divisibility error
                    raise ValueError(
                        f"sharded TrainStep: batch dim0={b.shape[0]} "
                        f"is not divisible by the mesh degree "
                        f"{self._deg}; use drop_last=True (Engine.fit "
                        f"does) or pad the tail batch")
            if getattr(self, "_mode", "1d") == "2d":
                self._place_params_2d(sd)
            else:
                self._place_replicated(sd)
            for k in self._trainable:
                self._refresh_state(k, sd[k])
        params = {k: sd[k]._value for k in self._trainable}
        frozen_vals = {k: sd[k]._value for k in self._frozen}
        return sd, params, frozen_vals, batch_vals

    def lower(self, *batch):
        """AOT-lower the fused step with the current params/shardings
        (used by DistModel.dist_main_program, the dist-attr read-back,
        and verify_sharded_update's HLO assertions)."""
        sd, params, frozen_vals, batch_vals = self._gather_inputs(batch)
        self._ensure_built(batch_vals)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        # fixed dummy key: lowering must not perturb the training RNG
        # stream (the key value cannot affect the lowered HLO)
        key = jax.random.PRNGKey(0)
        return self._step_fn.lower(params, frozen_vals, self._opt_states,
                                   lr, key, *batch_vals)

    def compiled_stats(self, *batch) -> Dict[str, Any]:
        """FLOPs + static memory sizes of the compiled fused step —
        the telemetry source for MFU (cost_analysis) and HBM headroom
        (memory_analysis).  AOT lower+compile of the SAME traced body
        (cached per instance: one extra compile, ever).  XLA reports
        PER-DEVICE numbers: under dp=8 sharding the flops are 1/8 of
        the global program — divide by per-chip peak for MFU, never by
        peak * device_count."""
        cached = getattr(self, "_compiled_stats", None)
        if cached is not None:
            return cached
        compiled = self.lower(*batch).compile()
        stats: Dict[str, Any] = {}
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            for src, dst in (("flops", "flops"),
                             ("bytes accessed", "bytes_accessed")):
                if ca.get(src):
                    stats[dst] = float(ca[src])
        except Exception:                             # noqa: BLE001
            pass
        try:
            ma = compiled.memory_analysis()
            for attr, dst in (
                    ("temp_size_in_bytes", "temp_bytes"),
                    ("argument_size_in_bytes", "argument_bytes"),
                    ("output_size_in_bytes", "output_bytes"),
                    ("generated_code_size_in_bytes", "code_bytes")):
                v = getattr(ma, attr, None)
                if v:
                    stats[dst] = int(v)
        except Exception:                             # noqa: BLE001
            pass
        self._compiled_stats = stats
        return stats

    def __call__(self, *batch):
        sd, params, frozen_vals, batch_vals = self._gather_inputs(batch)
        self._ensure_built(batch_vals)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.next_key()
        loss, new_params, new_states, new_bufs = self._step_fn(
            params, frozen_vals, self._opt_states, lr, key, *batch_vals)
        for k, v in new_params.items():
            sd[k]._value = v
        # persist traced buffer updates (BatchNorm running stats)
        for k, v in new_bufs.items():
            sd[k]._value = v
        # update the per-param state DICTS in place: optimizer._state
        # holds the same dict objects, so optimizer.state_dict() stays
        # valid after the donated buffers die
        for k, nst in new_states.items():
            self._opt_states[k].update(nst)
        if getattr(self, "_mode", None) == "2d":
            # static per-dispatch param-gather payload (per chip)
            self._m_gather_bytes.inc(self._gather_bytes_per_step)
        if isinstance(self.optimizer._learning_rate, object) and \
                hasattr(self.optimizer._learning_rate, "step"):
            pass  # caller drives the scheduler
        self.optimizer._global_step += 1
        return Tensor._from_value(loss)
