"""OpcodeExecutor: a CPython-3.12 bytecode interpreter for SOT tracing.

Reference analog: python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py:1473 (symbolic bytecode interpretation) and the
eval-frame hook paddle/fluid/pybind/eval_frame.c.

Design (trace-by-execution — see package docstring):

- Values on the simulated stack are *real* objects.  Framework ops execute
  eagerly (and are recorded at the dispatch choke point by the installed
  Recorder); pure-Python data flow (containers, arithmetic on scalars,
  calls) is interpreted opcode-by-opcode.
- `scan_code` statically whitelists the opcode set BEFORE execution, so
  the interpreter never aborts mid-frame (side effects run exactly once).
  try/except/finally, `with`, `raise` and imports are interpreted
  natively: exceptions unwind through the CPython-3.12 exception table
  (co_exceptiontable) exactly like the real frame would, so a traced
  function containing a `with autocast()` or try/except body still
  produces a compiled region — real-value execution makes the
  reference's resume-function machinery unnecessary (the handler simply
  keeps executing).  Generator *calls* run natively (their tensor work
  is still recorded at dispatch); only frames that ARE generators — and
  `match` statements — are skipped wholesale.
- Dynamic graph breaks (a jump conditioned on a Tensor, iteration over a
  non-tensor iterator of unknown purity, etc.) do NOT stop execution: the
  interpreter poisons the Recorder and keeps evaluating with concrete
  values, so the call still returns the correct eager result.
- User-defined plain Python functions reachable by CALL are *inlined*
  (interpreted in a nested frame) when their code passes scan_code, so
  breaks inside helpers are detected; library calls (paddle_tpu.*, jax,
  numpy, builtins) execute natively — their tensor work is recorded at
  dispatch, and host materialization inside them is caught by the
  Tensor-level poison net.
- LOAD_GLOBAL / LOAD_DEREF of scalar-like values register guards with the
  Recorder so a changed global invalidates the cached program.
"""
from __future__ import annotations

import dis
import operator
import types
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class GraphBreakReason(Exception):
    """Raised only by scan_code users — never escapes run()."""


class InterpreterInternalError(BaseException):
    """Interpreter bug / unsupported construct.  Derives from
    BaseException so user-level ``except Exception`` handlers inside the
    interpreted frame can never swallow it."""


class _NullType:
    __slots__ = ()

    def __repr__(self):
        return "<NULL>"


NULL = _NullType()

_CO_GENERATOR = 0x20
_CO_COROUTINE = 0x80
_CO_ASYNC_GENERATOR = 0x200
_CO_VARARGS = 0x04
_CO_VARKEYWORDS = 0x08

# opcode families the interpreter implements (CPython 3.12)
SUPPORTED_OPS = frozenset([
    "RESUME", "CACHE", "NOP", "EXTENDED_ARG", "PRECALL",
    "POP_TOP", "COPY", "SWAP", "PUSH_NULL",
    "LOAD_CONST", "RETURN_CONST", "RETURN_VALUE",
    "LOAD_FAST", "LOAD_FAST_CHECK", "LOAD_FAST_AND_CLEAR", "STORE_FAST",
    "DELETE_FAST",
    "LOAD_GLOBAL", "LOAD_NAME",
    "LOAD_DEREF", "STORE_DEREF", "LOAD_CLOSURE", "MAKE_CELL",
    "COPY_FREE_VARS",
    "LOAD_ATTR", "STORE_ATTR",
    "BINARY_OP", "UNARY_NEGATIVE", "UNARY_NOT", "UNARY_INVERT",
    "COMPARE_OP", "IS_OP", "CONTAINS_OP",
    "POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE", "POP_JUMP_IF_NONE",
    "POP_JUMP_IF_NOT_NONE", "JUMP_FORWARD", "JUMP_BACKWARD",
    "JUMP_BACKWARD_NO_INTERRUPT",
    "GET_ITER", "FOR_ITER", "END_FOR",
    "BUILD_TUPLE", "BUILD_LIST", "BUILD_MAP", "BUILD_SET",
    "BUILD_CONST_KEY_MAP", "BUILD_SLICE", "BUILD_STRING",
    "LIST_EXTEND", "LIST_APPEND", "SET_ADD", "SET_UPDATE", "MAP_ADD",
    "DICT_MERGE", "DICT_UPDATE", "FORMAT_VALUE",
    "BINARY_SUBSCR", "STORE_SUBSCR", "DELETE_SUBSCR",
    "PUSH_EXC_INFO", "POP_EXCEPT", "RERAISE", "CHECK_EXC_MATCH",
    "RAISE_VARARGS", "LOAD_ASSERTION_ERROR",
    "BEFORE_WITH", "WITH_EXCEPT_START",
    "IMPORT_NAME", "IMPORT_FROM",
    "BINARY_SLICE", "STORE_SLICE",
    "UNPACK_SEQUENCE", "UNPACK_EX",
    "CALL", "KW_NAMES", "CALL_FUNCTION_EX", "CALL_INTRINSIC_1",
    "MAKE_FUNCTION", "RETURN_GENERATOR",
])

_SUPPORTED_INTRINSICS = frozenset([
    "INTRINSIC_1_INVALID", "INTRINSIC_UNARY_POSITIVE",
    "INTRINSIC_LIST_TO_TUPLE",
])

# modules whose functions execute natively (never inlined) — the framework
# itself plus numeric/std libraries whose internals are trace-safe
_NATIVE_PREFIXES = (
    "paddle_tpu", "jax", "numpy", "builtins", "math", "functools",
    "itertools", "operator", "collections", "typing", "contextlib",
    "threading", "copy", "abc", "enum", "warnings", "os", "re",
)

_BINARY_OPS = {
    "+": operator.add, "-": operator.sub, "*": operator.mul,
    "/": operator.truediv, "//": operator.floordiv, "%": operator.mod,
    "**": operator.pow, "<<": operator.lshift, ">>": operator.rshift,
    "&": operator.and_, "|": operator.or_, "^": operator.xor,
    "@": operator.matmul,
    "+=": operator.iadd, "-=": operator.isub, "*=": operator.imul,
    "/=": operator.itruediv, "//=": operator.ifloordiv,
    "%=": operator.imod, "**=": operator.ipow, "<<=": operator.ilshift,
    ">>=": operator.irshift, "&=": operator.iand, "|=": operator.ior,
    "^=": operator.ixor, "@=": operator.imatmul,
}

_COMPARE_OPS = {
    "<": operator.lt, "<=": operator.le, "==": operator.eq,
    "!=": operator.ne, ">": operator.gt, ">=": operator.ge,
}


_HAS_EXC_TABLE_PARSER = hasattr(dis, "_parse_exception_table")


def scan_code(code: types.CodeType) -> Optional[str]:
    """Return None if the interpreter fully supports this code object,
    else a human-readable reason (→ skip frame, run eagerly)."""
    if code.co_flags & (_CO_GENERATOR | _CO_COROUTINE | _CO_ASYNC_GENERATOR):
        return "generator/coroutine"
    if code.co_exceptiontable and not _HAS_EXC_TABLE_PARSER:
        # without the table the handlers can't run — skipping the frame
        # is correct; silently ignoring the table would NOT be
        return "exception table parser unavailable"
    for ins in dis.get_instructions(code):
        if ins.opname not in SUPPORTED_OPS:
            return f"unsupported opcode {ins.opname}"
        if ins.opname == "CALL_INTRINSIC_1" \
                and ins.argrepr not in _SUPPORTED_INTRINSICS:
            return f"unsupported intrinsic {ins.argrepr}"
        if ins.opname == "RETURN_GENERATOR":
            return "generator"
    return None


def _is_tensor(v) -> bool:
    from ...core.tensor import Tensor
    return isinstance(v, Tensor)


class OpcodeExecutor:
    """Interprets one frame (and inlined user callees) with real values."""

    def __init__(self, recorder, depth: int = 0, exc_cell=None):
        self.recorder = recorder
        self.depth = depth
        # the "current exception" is per-TRACE, not per-frame (CPython
        # keeps it in the thread state): a bare `raise` in an inlined
        # callee re-raises the caller's handled exception.  The
        # PUSH_EXC_INFO / POP_EXCEPT save-restore discipline keeps
        # nesting correct over this single shared cell.
        self.exc_cell = exc_cell if exc_cell is not None else [None]

    # -- inlining decision ---------------------------------------------------
    def _inlinable(self, fn) -> bool:
        if self.depth >= 8:
            return False
        target = fn
        if isinstance(target, types.MethodType):
            target = target.__func__
        if not isinstance(target, types.FunctionType):
            return False
        mod = getattr(target, "__module__", None) or ""
        for p in _NATIVE_PREFIXES:
            if mod == p or mod.startswith(p + "."):
                return False
        if getattr(target, "_not_to_static", False):
            return False
        return scan_code(target.__code__) is None

    # -- frame entry ---------------------------------------------------------
    def run(self, fn, args: tuple, kwargs: dict):
        """Interpret ``fn(*args, **kwargs)`` and return its result."""
        target = fn
        self_arg = None
        if isinstance(target, types.MethodType):
            self_arg = target.__self__
            target = target.__func__
        code = target.__code__
        f_locals = self._bind(target, code,
                              (self_arg,) + tuple(args)
                              if self_arg is not None else tuple(args),
                              kwargs)
        return self._run_code(code, f_locals,
                              target.__globals__,
                              target.__closure__ or (),
                              getattr(target, "__builtins__", None))

    def _bind(self, fn, code, args, kwargs) -> Dict[str, Any]:
        import inspect
        try:
            sig = inspect.signature(fn)
            bound = sig.bind(*args, **kwargs)
            bound.apply_defaults()
            f_locals = dict(bound.arguments)
        except (TypeError, ValueError):
            # let the real call raise the real error
            raise
        # normalize *args/**kwargs slots to their co_varnames names
        return f_locals

    # -- main loop -----------------------------------------------------------
    def _run_code(self, code, f_locals, f_globals, closure, builtins_ns):
        instructions = list(dis.get_instructions(code))
        by_offset = {ins.offset: i for i, ins in enumerate(instructions)}
        # CPython-3.12 zero-cost exception handling: the compiled
        # exception table maps instruction ranges to (handler, stack
        # depth, push-lasti); unwinding replays exactly those semantics.
        # scan_code rejects try/except frames when the parser is
        # unavailable, so a non-empty table always parses here.
        exc_table = dis._parse_exception_table(code) \
            if code.co_exceptiontable else []
        current_exc = self.exc_cell
        stack: List[Any] = []
        # cells: co_cellvars are fresh cells (MAKE_CELL initializes them,
        # possibly from a local); co_freevars come from the closure
        cells: Dict[str, Any] = {}
        for i, name in enumerate(code.co_freevars):
            cells[name] = closure[i]
        kw_names: Tuple[str, ...] = ()
        builtins_mod = builtins_ns
        if builtins_mod is None:
            import builtins as _b
            builtins_mod = _b
        builtins_dict = builtins_mod.__dict__ \
            if hasattr(builtins_mod, "__dict__") else builtins_mod

        rec = self.recorder
        ip = 0
        while True:
            ins = instructions[ip]
            op = ins.opname
            arg = ins.arg

            try:
                if op in ("RESUME", "CACHE", "NOP", "EXTENDED_ARG", "PRECALL",
                          "MAKE_CELL", "COPY_FREE_VARS"):
                    if op == "MAKE_CELL":
                        name = ins.argval
                        cells[name] = types.CellType(f_locals[name]) \
                            if name in f_locals else types.CellType()
                    ip += 1
                    continue

                if op == "POP_TOP":
                    stack.pop()
                elif op == "COPY":
                    stack.append(stack[-arg])
                elif op == "SWAP":
                    stack[-1], stack[-arg] = stack[-arg], stack[-1]
                elif op == "PUSH_NULL":
                    stack.append(NULL)

                elif op == "LOAD_CONST":
                    stack.append(ins.argval)
                elif op == "RETURN_CONST":
                    return ins.argval
                elif op == "RETURN_VALUE":
                    return stack.pop()

                elif op in ("LOAD_FAST", "LOAD_FAST_CHECK"):
                    if ins.argval not in f_locals:
                        # the exception CPython raises — not the
                        # machinery's KeyError, which a user handler
                        # could wrongly catch
                        raise UnboundLocalError(
                            f"cannot access local variable "
                            f"'{ins.argval}' where it is not "
                            f"associated with a value")
                    stack.append(f_locals[ins.argval])
                elif op == "LOAD_FAST_AND_CLEAR":
                    stack.append(f_locals.pop(ins.argval, None))
                elif op == "STORE_FAST":
                    f_locals[ins.argval] = stack.pop()
                elif op == "DELETE_FAST":
                    if ins.argval not in f_locals:
                        raise UnboundLocalError(
                            f"cannot access local variable "
                            f"'{ins.argval}' where it is not "
                            f"associated with a value")
                    del f_locals[ins.argval]

                elif op in ("LOAD_GLOBAL", "LOAD_NAME"):
                    if op == "LOAD_GLOBAL" and arg & 1:
                        stack.append(NULL)
                    name = ins.argval
                    if name in f_globals:
                        val = f_globals[name]
                        self._guard_env("global", name, val)
                    elif name in builtins_dict:
                        val = builtins_dict[name]
                    else:
                        raise NameError(f"name '{name}' is not defined")
                    stack.append(val)

                elif op in ("LOAD_DEREF", "LOAD_CLOSURE"):
                    name = ins.argval
                    if op == "LOAD_CLOSURE":
                        stack.append(cells[name])
                    else:
                        val = cells[name].cell_contents
                        self._guard_env("deref", name, val)
                        stack.append(val)
                elif op == "STORE_DEREF":
                    name = ins.argval
                    if name not in cells:
                        cells[name] = types.CellType()
                    cells[name].cell_contents = stack.pop()

                elif op == "LOAD_ATTR":
                    owner = stack.pop()
                    name = ins.argval
                    if arg & 1:
                        # method form: push (unbound, self) or (NULL, attr)
                        attr = getattr(owner, name)
                        if isinstance(attr, types.MethodType) \
                                and attr.__self__ is owner:
                            stack.append(attr.__func__)
                            stack.append(owner)
                        else:
                            stack.append(NULL)
                            stack.append(attr)
                    else:
                        stack.append(getattr(owner, name))
                elif op == "STORE_ATTR":
                    owner = stack.pop()
                    val = stack.pop()
                    setattr(owner, ins.argval, val)

                elif op == "BINARY_OP":
                    rhs = stack.pop()
                    lhs = stack.pop()
                    fn = _BINARY_OPS.get(ins.argrepr)
                    if fn is None:
                        raise InterpreterInternalError(
                        f"BINARY_OP {ins.argrepr}")
                    stack.append(fn(lhs, rhs))
                elif op == "UNARY_NEGATIVE":
                    stack.append(-stack.pop())
                elif op == "UNARY_NOT":
                    v = stack.pop()
                    if _is_tensor(v):
                        rec.poison("`not` on a tensor value")
                    stack.append(not v)
                elif op == "UNARY_INVERT":
                    stack.append(~stack.pop())

                elif op == "COMPARE_OP":
                    rhs = stack.pop()
                    lhs = stack.pop()
                    fn = _COMPARE_OPS.get(ins.argrepr.strip())
                    if fn is None:
                        raise InterpreterInternalError(
                        f"COMPARE_OP {ins.argrepr}")
                    stack.append(fn(lhs, rhs))
                elif op == "IS_OP":
                    rhs = stack.pop()
                    lhs = stack.pop()
                    stack.append((lhs is not rhs) if arg else (lhs is rhs))
                elif op == "CONTAINS_OP":
                    rhs = stack.pop()
                    lhs = stack.pop()
                    if _is_tensor(rhs) or _is_tensor(lhs):
                        rec.poison("`in` on a tensor value")
                    res = lhs in rhs
                    stack.append((not res) if arg else res)

                elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE"):
                    v = stack.pop()
                    if _is_tensor(v):
                        rec.poison("data-dependent branch on tensor value")
                    truth = bool(v)
                    want = (op == "POP_JUMP_IF_TRUE")
                    if truth == want:
                        ip = by_offset[ins.argval]
                        continue
                elif op in ("POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                    v = stack.pop()
                    is_none = v is None
                    want = (op == "POP_JUMP_IF_NONE")
                    if is_none == want:
                        ip = by_offset[ins.argval]
                        continue
                elif op in ("JUMP_FORWARD", "JUMP_BACKWARD",
                            "JUMP_BACKWARD_NO_INTERRUPT"):
                    ip = by_offset[ins.argval]
                    continue

                elif op == "GET_ITER":
                    v = stack.pop()
                    stack.append(iter(v))
                elif op == "FOR_ITER":
                    it = stack[-1]
                    try:
                        stack.append(next(it))
                    except StopIteration:
                        # 3.12: leave iterator; push exhaustion marker; jump
                        # to the END_FOR at the target, which pops both
                        stack.append(None)
                        ip = by_offset[ins.argval]
                        continue
                elif op == "END_FOR":
                    stack.pop()
                    stack.pop()

                elif op == "BUILD_TUPLE":
                    vals = stack[len(stack) - arg:] if arg else []
                    del stack[len(stack) - arg:]
                    stack.append(tuple(vals))
                elif op == "BUILD_LIST":
                    vals = stack[len(stack) - arg:] if arg else []
                    del stack[len(stack) - arg:]
                    stack.append(list(vals))
                elif op == "BUILD_SET":
                    vals = stack[len(stack) - arg:] if arg else []
                    del stack[len(stack) - arg:]
                    stack.append(set(vals))
                elif op == "BUILD_MAP":
                    items = stack[len(stack) - 2 * arg:] if arg else []
                    del stack[len(stack) - 2 * arg:]
                    stack.append({items[i]: items[i + 1]
                                  for i in range(0, len(items), 2)})
                elif op == "BUILD_CONST_KEY_MAP":
                    keys = stack.pop()
                    vals = stack[len(stack) - arg:]
                    del stack[len(stack) - arg:]
                    stack.append(dict(zip(keys, vals)))
                elif op == "BUILD_SLICE":
                    if arg == 3:
                        step = stack.pop()
                    else:
                        step = None
                    stop = stack.pop()
                    start = stack.pop()
                    stack.append(slice(start, stop, step))
                elif op == "BUILD_STRING":
                    parts = stack[len(stack) - arg:]
                    del stack[len(stack) - arg:]
                    stack.append("".join(parts))
                elif op == "FORMAT_VALUE":
                    have_spec = arg & 0x04
                    spec = stack.pop() if have_spec else ""
                    v = stack.pop()
                    conv = arg & 0x03
                    if conv == 1:
                        v = str(v)
                    elif conv == 2:
                        v = repr(v)
                    elif conv == 3:
                        v = ascii(v)
                    stack.append(format(v, spec))

                elif op == "LIST_EXTEND":
                    seq = stack.pop()
                    stack[-arg].extend(seq)
                elif op == "LIST_APPEND":
                    v = stack.pop()
                    stack[-arg].append(v)
                elif op == "SET_ADD":
                    v = stack.pop()
                    stack[-arg].add(v)
                elif op == "SET_UPDATE":
                    seq = stack.pop()
                    stack[-arg].update(seq)
                elif op == "MAP_ADD":
                    value = stack.pop()
                    key_ = stack.pop()
                    stack[-arg][key_] = value
                elif op in ("DICT_MERGE", "DICT_UPDATE"):
                    other = stack.pop()
                    stack[-arg].update(other)

                elif op == "BINARY_SUBSCR":
                    idx = stack.pop()
                    obj = stack.pop()
                    stack.append(obj[idx])
                elif op == "STORE_SUBSCR":
                    idx = stack.pop()
                    obj = stack.pop()
                    val = stack.pop()
                    obj[idx] = val
                elif op == "DELETE_SUBSCR":
                    idx = stack.pop()
                    obj = stack.pop()
                    del obj[idx]
                elif op == "BINARY_SLICE":
                    stop = stack.pop()
                    start = stack.pop()
                    obj = stack.pop()
                    stack.append(obj[start:stop])
                elif op == "STORE_SLICE":
                    stop = stack.pop()
                    start = stack.pop()
                    obj = stack.pop()
                    val = stack.pop()
                    obj[start:stop] = val

                elif op == "UNPACK_SEQUENCE":
                    seq = stack.pop()
                    vals = list(seq)
                    if len(vals) != arg:
                        raise ValueError(
                            f"not enough values to unpack (expected {arg})")
                    stack.extend(reversed(vals))
                elif op == "UNPACK_EX":
                    before = arg & 0xFF
                    after = arg >> 8
                    seq = list(stack.pop())
                    rest = seq[before:len(seq) - after] \
                        if after else seq[before:]
                    tail = seq[len(seq) - after:] if after else []
                    for v in reversed(tail):
                        stack.append(v)
                    stack.append(rest)
                    for v in reversed(seq[:before]):
                        stack.append(v)

                elif op == "KW_NAMES":
                    kw_names = ins.argval
                elif op == "CALL":
                    argc = arg
                    call_args = stack[len(stack) - argc:] if argc else []
                    del stack[len(stack) - argc:]
                    self_or_null = stack.pop()
                    callable_ = stack.pop()
                    if callable_ is NULL:
                        callable_ = self_or_null
                    elif self_or_null is not NULL:
                        call_args = [self_or_null] + call_args
                    if kw_names:
                        n_kw = len(kw_names)
                        kw = dict(zip(kw_names, call_args[len(call_args) - n_kw:]))
                        call_args = call_args[:len(call_args) - n_kw]
                        kw_names = ()
                    else:
                        kw = {}
                    stack.append(self._call(callable_, call_args, kw))
                elif op == "CALL_FUNCTION_EX":
                    kw = stack.pop() if arg & 1 else {}
                    pos = list(stack.pop())
                    self_or_null = stack.pop()
                    callable_ = stack.pop()
                    if callable_ is NULL:
                        callable_ = self_or_null
                    elif self_or_null is not NULL:
                        pos = [self_or_null] + pos
                    stack.append(self._call(callable_, pos, dict(kw)))
                elif op == "CALL_INTRINSIC_1":
                    which = ins.argrepr
                    v = stack.pop()
                    if which == "INTRINSIC_UNARY_POSITIVE":
                        stack.append(+v)
                    elif which == "INTRINSIC_LIST_TO_TUPLE":
                        stack.append(tuple(v))
                    else:
                        raise InterpreterInternalError(f"intrinsic {which}")

                # -- exception machinery (3.12 zero-cost scheme) --------
                elif op == "PUSH_EXC_INFO":
                    exc = stack.pop()
                    stack.append(current_exc[0])
                    stack.append(exc)
                    current_exc[0] = exc
                elif op == "POP_EXCEPT":
                    current_exc[0] = stack.pop()
                elif op == "RERAISE":
                    # oparg != 0: a lasti slot sits below the exception;
                    # it is NOT popped (the unwinder discards it)
                    exc = stack.pop()
                    raise exc
                elif op == "CHECK_EXC_MATCH":
                    typ = stack.pop()
                    stack.append(isinstance(stack[-1], typ))
                elif op == "RAISE_VARARGS":
                    if arg == 0:
                        if current_exc[0] is None:
                            raise RuntimeError(
                                "No active exception to reraise")
                        raise current_exc[0]
                    cause = stack.pop() if arg == 2 else None
                    exc = stack.pop()
                    if isinstance(exc, type):
                        exc = exc()
                    if arg == 2:
                        raise exc from cause
                    raise exc
                elif op == "LOAD_ASSERTION_ERROR":
                    stack.append(AssertionError)

                # -- with ----------------------------------------------
                elif op == "BEFORE_WITH":
                    mgr = stack.pop()
                    exit_fn = type(mgr).__exit__.__get__(mgr)
                    enter_fn = type(mgr).__enter__
                    stack.append(exit_fn)
                    stack.append(enter_fn(mgr))
                elif op == "WITH_EXCEPT_START":
                    exc = stack[-1]
                    exit_fn = stack[-4]
                    stack.append(exit_fn(type(exc), exc,
                                         exc.__traceback__))

                # -- imports -------------------------------------------
                elif op == "IMPORT_NAME":
                    fromlist = stack.pop()
                    level = stack.pop()
                    stack.append(__import__(
                        ins.argval, f_globals, None, fromlist or (),
                        level or 0))
                elif op == "IMPORT_FROM":
                    stack.append(getattr(stack[-1], ins.argval))

                elif op == "MAKE_FUNCTION":
                    fcode = stack.pop()
                    closure_t = stack.pop() if arg & 0x08 else None
                    annotations = stack.pop() if arg & 0x04 else None
                    kwdefaults = stack.pop() if arg & 0x02 else None
                    defaults = stack.pop() if arg & 0x01 else None
                    new_fn = types.FunctionType(
                        fcode, f_globals, fcode.co_name,
                        tuple(defaults) if defaults else None,
                        tuple(closure_t) if closure_t else None)
                    if kwdefaults:
                        new_fn.__kwdefaults__ = dict(kwdefaults)
                    if annotations:
                        new_fn.__annotations__ = dict(annotations)
                    stack.append(new_fn)

                else:   # pragma: no cover — scan_code should prevent this
                    raise InterpreterInternalError(f"unhandled opcode {op}")

                ip += 1
            except InterpreterInternalError:
                raise
            except Exception as e:
                # unwind through the frame's exception table (the same
                # zero-cost scheme the real CPython frame would use)
                ent = None
                for cand in exc_table:
                    if cand.start <= ins.offset < cand.end:
                        ent = cand
                        break
                if ent is None:
                    raise
                del stack[ent.depth:]
                if ent.lasti:
                    stack.append(ins.offset)
                stack.append(e)
                ip = by_offset[ent.target]
                continue


    # -- calls ---------------------------------------------------------------
    def _call(self, callable_, args: list, kwargs: dict):
        if self._inlinable(callable_):
            sub = OpcodeExecutor(self.recorder, self.depth + 1,
                                 exc_cell=self.exc_cell)
            return sub.run(callable_, tuple(args), kwargs)
        return callable_(*args, **kwargs)

    # -- guards --------------------------------------------------------------
    def _guard_env(self, kind: str, name: str, val):
        if self.depth > 0:
            return   # guard only the entry frame's environment
        if isinstance(val, (int, float, bool, str, bytes, type(None))):
            self.recorder.add_env_guard(kind, name, val)
        else:
            # objects (layers, modules, functions) guard by identity: a
            # rebound global must invalidate the cached program
            self.recorder.add_env_guard(kind + "_id", name, id(val))
