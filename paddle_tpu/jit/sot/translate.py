"""symbolic_translate / SOTFunction: the SOT entry point.

Reference analog: python/paddle/jit/sot/translate.py (installs the
eval-frame hook) + eval_frame_callback.py:52 (guard check, compile cache,
graph-break fallback).  Here the "frame hook" is SOTFunction.__call__:

call 1 (per guard set): interpret the frame bytecode with OpcodeExecutor
    while a Recorder logs every dispatched op → StatementIR → jax.jit
    replay program.  The call itself IS a correct eager call (real values,
    single side effects), so its result is returned directly.
call 2+: guards hit → run the compiled XLA module through apply_op (one
    tape node; backward runs the compiled VJP), apply buffer write-backs.
poisoned / unsupported frames: cached as "skip" — run eagerly forever,
    with the break reason kept for introspection.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax

from ...core.tensor import Tensor
from ...core import dispatch as _dispatch
from ...core.dispatch import apply_op
from ...ops import random as _random
from .statement_ir import Recorder, StatementIR, TraceInvalid, build_replay
from .opcode_executor import OpcodeExecutor, scan_code


def _leaf_sig(a):
    if isinstance(a, Tensor):
        return ("T", tuple(a._value.shape), str(a._value.dtype))
    if isinstance(a, (int, float, str, bool, type(None))):
        return ("P", a)
    return ("P", repr(a))


class _CompiledEntry:
    __slots__ = ("jit_fn", "ir", "env_guards")

    def __init__(self, jit_fn, ir, env_guards):
        self.jit_fn = jit_fn
        self.ir = ir
        self.env_guards = env_guards


class SOTFunction:
    """Bytecode-traced callable (reference SymbolicStaticFunction,
    python/paddle/jit/dy2static/program_translator.py:704)."""

    def __init__(self, function, input_spec=None, build_strategy=None):
        self._fn = function
        self._cache: Dict[Any, Any] = {}
        self._layers = None
        self.graph_break_reason: Optional[str] = None
        self.__name__ = getattr(function, "__name__", "sot_fn")
        functools.update_wrapper(self, function,
                                 assigned=("__doc__", "__module__"),
                                 updated=())

    # -- helpers -------------------------------------------------------------
    def _eager_call(self):
        from ...nn.layer_base import Layer
        return self._fn.forward if isinstance(self._fn, Layer) else self._fn

    def _target_code(self):
        from ...nn.layer_base import Layer
        fn = self._eager_call()
        fn = getattr(fn, "__func__", fn)
        return getattr(fn, "__code__", None)

    def _modes(self):
        from ..api import _find_layers
        if self._layers is None:
            self._layers = _find_layers(self._fn)
        return tuple(l.training for layer in self._layers
                     for _, l in layer.named_sublayers(include_self=True))

    def _check_env_guards(self, guards) -> bool:
        fn = self._eager_call()
        fn = getattr(fn, "__func__", fn)
        glb = getattr(fn, "__globals__", {})
        code = getattr(fn, "__code__", None)
        closure = getattr(fn, "__closure__", None) or ()
        freevars = code.co_freevars if code is not None else ()
        cellmap = dict(zip(freevars, closure))
        for kind, name, expected in guards:
            if kind == "global":
                if glb.get(name, _MISSING) != expected:
                    return False
            elif kind == "global_id":
                if id(glb.get(name, _MISSING)) != expected:
                    return False
            elif kind in ("deref", "deref_id"):
                cell = cellmap.get(name)
                if cell is None:
                    return False
                try:
                    val = cell.cell_contents
                except ValueError:
                    return False
                if kind == "deref":
                    if val != expected:
                        return False
                elif id(val) != expected:
                    return False
        return True

    # -- call ----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        from ..api import _TO_STATIC_ENABLED
        if not _TO_STATIC_ENABLED[0] \
                or getattr(self._fn, "_not_to_static", False) \
                or _dispatch._sot_recorder[0] is not None:
            # disabled, opted out, or already inside an outer SOT trace
            # (the outer recorder sees our ops straight through dispatch)
            return self._eager_call()(*args, **kwargs)

        code = self._target_code()
        if code is None:
            return self._eager_call()(*args, **kwargs)
        scan_reason = scan_code(code)
        if scan_reason is not None:
            self.graph_break_reason = scan_reason
            return self._eager_call()(*args, **kwargs)

        flat_args, arg_tree = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        flat_args = [Tensor(a) if isinstance(a, np.ndarray) else a
                     for a in flat_args]
        amp = _dispatch._amp_state
        amp_sig = (amp["enabled"], str(amp["dtype"]), amp["level"])
        key = (str(arg_tree), tuple(_leaf_sig(a) for a in flat_args),
               self._modes(), amp_sig)

        entry = self._cache.get(key)
        if isinstance(entry, _CompiledEntry):
            if self._captures_valid(entry) \
                    and self._check_env_guards(entry.env_guards):
                return self._run_compiled(entry, arg_tree, flat_args)
            del self._cache[key]   # stale: re-record below
            entry = None
        elif entry is not None:    # ("skip", reason)
            self.graph_break_reason = entry[1]
            return self._eager_call()(*args, **kwargs)

        return self._record(key, arg_tree, flat_args)

    # -- recording path ------------------------------------------------------
    def _record(self, key, arg_tree, flat_args):
        args, kwargs = jax.tree_util.tree_unflatten(arg_tree, flat_args)
        rec = Recorder()
        for a in flat_args:
            if isinstance(a, Tensor):
                rec.declare_input(a)

        _dispatch._sot_recorder[0] = rec
        try:
            executor = OpcodeExecutor(rec)
            result = executor.run(self._eager_call(), args, kwargs)
        finally:
            _dispatch._sot_recorder[0] = None

        try:
            ir = rec.finalize(result)
        except TraceInvalid as e:
            self.graph_break_reason = str(e)
            self._cache[key] = ("skip", str(e))
            return result

        _log_captured_ir(ir)
        jit_fn = jax.jit(build_replay(ir))
        self._cache[key] = _CompiledEntry(jit_fn, ir, rec.env_guards)
        return result

    # -- compiled path -------------------------------------------------------
    def _captures_valid(self, entry) -> bool:
        for t, _ in entry.ir.captures:
            if t._value is None:
                return False
        return True

    def _run_compiled(self, entry, arg_tree, flat_args):
        ir = entry.ir
        base_key = _random.next_key()
        capture_tensors = [t for t, _ in ir.captures]
        input_tensors = [a for a in flat_args if isinstance(a, Tensor)]
        outs = apply_op(f"sot_compiled::{self.__name__}", entry.jit_fn,
                        (base_key, *capture_tensors, *input_tensors))
        if not isinstance(outs, tuple):
            outs = (outs,)
        n_wb = len(ir.writebacks)
        if n_wb:
            for (t, _), new in zip(ir.writebacks, outs[len(outs) - n_wb:]):
                t._value = new._value
            outs = outs[: len(outs) - n_wb]
        # reassemble the return-value tree: tensor leaves from outputs,
        # non-tensor leaves from baked constants
        leaves = []
        it = iter(outs)
        for sym, const in zip(ir.out_syms, ir.out_consts):
            leaves.append(next(it) if sym is not None else const)
        return jax.tree_util.tree_unflatten(ir.out_tree, leaves)


class _Missing:
    def __eq__(self, other):
        return False

    def __repr__(self):
        return "<missing>"


_MISSING = _Missing()


def symbolic_translate(fn=None, **kwargs):
    """Parity: paddle.jit.sot.symbolic_translate (translate.py:99)."""
    if fn is None:
        return lambda f: SOTFunction(f, **kwargs)
    return SOTFunction(fn, **kwargs)


def _log_captured_ir(ir):
    """jit.set_code_level hook: log the captured StatementIR (our analog
    of the reference translator's transformed-code logging,
    paddle/jit/dy2static/logging_utils.py)."""
    import logging
    from .. import _TRANSLATOR_LOG
    lvl = _TRANSLATOR_LOG.get("code_level", -1)
    if lvl < 0:
        return
    lines = [f"StatementIR: {len(ir.statements)} statements, "
             f"{len(ir.input_syms)} inputs, {len(ir.captures)} captures"]
    for st in ir.statements:
        lines.append(f"  {st.name}")
    text = "\n".join(lines)
    logging.getLogger("paddle_tpu.jit").log(max(int(lvl), 1), text)
    if _TRANSLATOR_LOG.get("also_to_stdout"):
        print(text)
