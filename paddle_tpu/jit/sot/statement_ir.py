"""StatementIR: the linear op-trace recorded at the dispatch choke point.

Reference analog: python/paddle/jit/sot/symbolic/statement_ir.py (the IR the
OpcodeExecutor emits) + compile_cache.py (compilation into a partial
program).  Here a statement is the (pure jax fn, args) pair that
core.dispatch.apply_op executed; replay chains the same pure fns inside one
jax.jit, so the compiled artifact is a single XLA module.

Symbols are keyed on id(jax.Array).  jax arrays are immutable, and the
recorder keeps every seen array alive for the duration of the trace, so an
id uniquely names a value.  In-place Tensor ops swap `t._value`, which
automatically re-points the Tensor at the new symbol — aliasing is free.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class Statement:
    """One recorded op: name, the pure fn, and how to rebuild its args.

    arg_spec entries: ("s", sym) — a traced value; ("c", value) — a baked
    constant; ("r", slot) — an RNG key slot refreshed per replay.
    """

    __slots__ = ("name", "fn", "arg_spec", "kwargs", "cast_to", "out_syms")

    def __init__(self, name, fn, arg_spec, kwargs, cast_to, out_syms):
        self.name = name
        self.fn = fn
        self.arg_spec = arg_spec
        self.kwargs = kwargs
        self.cast_to = cast_to
        self.out_syms = out_syms

    def __repr__(self):
        args = ", ".join(
            f"%{s}" if k == "s" else f"rng{s}" if k == "r" else repr(s)
            for k, s in self.arg_spec)
        outs = ", ".join(f"%{s}" for s in self.out_syms)
        return f"{outs} = {self.name}({args})"


class StatementIR:
    """A finalized trace: inputs, captures, statements, outputs,
    mutation write-backs."""

    def __init__(self, input_syms, captures, statements, n_rng,
                 out_syms, out_tree, out_consts, writebacks):
        self.input_syms = input_syms          # syms of user tensor inputs
        self.captures = captures              # [(Tensor ref, sym)]
        self.statements = statements
        self.n_rng = n_rng
        self.out_syms = out_syms              # syms of tensor output leaves
        self.out_tree = out_tree              # treedef of the return value
        self.out_consts = out_consts          # non-tensor leaves (baked)
        self.writebacks = writebacks          # [(Tensor ref, sym)]

    def __repr__(self):
        body = "\n  ".join(repr(s) for s in self.statements)
        return (f"StatementIR(inputs={self.input_syms}, "
                f"captures={len(self.captures)}, rng={self.n_rng}, "
                f"writebacks={len(self.writebacks)})\n  {body}")


class TraceInvalid(Exception):
    """Recording cannot produce a replayable program (graph break)."""


class Recorder:
    """Collects statements from apply_op while a frame is interpreted.

    Installed into core.dispatch._sot_recorder for the duration of the
    recording call.  ``poisoned`` marks the trace non-replayable; execution
    continues (the recording call is a correct eager call regardless).
    """

    def __init__(self):
        self.statements: List[Statement] = []
        self._sym_of: Dict[int, int] = {}       # id(array) -> sym
        self._next_sym = 0
        self._keepalive: List[Any] = []         # pin arrays so ids are stable
        self._inputs: List[Tuple[Any, int, int]] = []  # (Tensor, sym, id0)
        self._captures: Dict[int, Tuple[Any, int]] = {}  # id(arr) -> (T, sym)
        self._rng_pending: Dict[int, Any] = {}  # id(key) -> key
        self._rng_slots: Dict[int, int] = {}    # id(key) -> slot
        self.poisoned = False
        self.reason: Optional[str] = None
        self.env_guards: List[Tuple[str, Any, Any]] = []

    # -- symbols -------------------------------------------------------------
    def _new_sym(self, arr) -> int:
        sym = self._next_sym
        self._next_sym += 1
        self._sym_of[id(arr)] = sym
        self._keepalive.append(arr)
        return sym

    def declare_input(self, tensor) -> int:
        sym = self._new_sym(tensor._value)
        self._inputs.append((tensor, sym, id(tensor._value)))
        return sym

    def input_sym_of(self, tensor):
        """The sym DECLARED for an input placeholder.  Resolving by the
        current value id is wrong when an aliasing op (same-shape
        reshape, no-op cast, ...) returned the placeholder's buffer and
        remapped it to the op's output sym."""
        for t, sym, _ in self._inputs:
            if t is tensor:
                return sym
        return self._sym_of.get(id(tensor._value))

    def register_rng_key(self, key):
        self._rng_pending[id(key)] = key
        self._keepalive.append(key)

    def poison(self, reason: str):
        if not self.poisoned:
            self.poisoned = True
            self.reason = reason

    def add_env_guard(self, kind: str, info: Any, expected: Any):
        self.env_guards.append((kind, info, expected))

    # -- recording (called from core.dispatch) -------------------------------
    def record(self, name, fn, tensor_args, kwargs, outs, multi_output,
               cast_to):
        if self.poisoned:
            return
        from ...core.tensor import Tensor
        arg_spec = []
        for a in tensor_args:
            if isinstance(a, Tensor):
                aid = id(a._value)
                sym = self._sym_of.get(aid)
                if sym is None:
                    sym = self._capture(a)
                arg_spec.append(("s", sym))
            elif isinstance(a, jax.Array):
                aid = id(a)
                if aid in self._rng_slots:
                    arg_spec.append(("r", self._rng_slots[aid]))
                elif aid in self._rng_pending:
                    slot = len(self._rng_slots)
                    self._rng_slots[aid] = slot
                    del self._rng_pending[aid]
                    arg_spec.append(("r", slot))
                elif aid in self._sym_of:
                    arg_spec.append(("s", self._sym_of[aid]))
                else:
                    # unknown raw array: bake (e.g. precomputed masks)
                    self._keepalive.append(a)
                    arg_spec.append(("c", a))
            elif isinstance(a, np.ndarray):
                arg_spec.append(("c", a))
            elif isinstance(a, (int, float, bool, str, bytes, type(None),
                                tuple, list, np.integer, np.floating)):
                arg_spec.append(("c", a))
            else:
                self.poison(f"op {name}: unrecordable arg {type(a)}")
                return
        for v in (kwargs or {}).values():
            if isinstance(v, (Tensor, jax.Array)):
                self.poison(f"op {name}: tensor-valued kwarg")
                return
        out_list = outs if isinstance(outs, tuple) else (outs,)
        out_syms = [self._new_sym(t._value) for t in out_list]
        self.statements.append(Statement(
            name, fn, arg_spec, dict(kwargs or {}), cast_to, out_syms))

    def _capture(self, tensor) -> int:
        aid = id(tensor._value)
        sym = self._new_sym(tensor._value)
        self._captures[aid] = (tensor, sym)
        return sym

    # -- finalize ------------------------------------------------------------
    def finalize(self, result) -> StatementIR:
        from ...core.tensor import Tensor
        if self.poisoned:
            raise TraceInvalid(self.reason)
        if self._rng_pending:
            raise TraceInvalid(
                "rng key drawn during trace but never reached a recorded "
                "statement (op draws its key through a closure)")

        flat, tree = jax.tree_util.tree_flatten(
            result, is_leaf=lambda x: isinstance(x, Tensor))
        out_syms, out_consts = [], []
        for leaf in flat:
            if isinstance(leaf, Tensor):
                sym = self._sym_of.get(id(leaf._value))
                if sym is None:
                    sym = self._capture(leaf)   # returned param/constant
                out_syms.append(sym)
                out_consts.append(None)
            else:
                out_syms.append(None)
                out_consts.append(leaf)

        # mutation write-backs: inputs or captures whose _value was swapped
        # to a traced array during the frame (BN running stats, in-place
        # ops on args) get the new value written back at replay
        writebacks = []
        seen = set()
        for tensor, sym, id0 in self._inputs:
            cur = id(tensor._value)
            if cur != id0 and id(tensor) not in seen:
                new_sym = self._sym_of.get(cur)
                if new_sym is None:
                    raise TraceInvalid("input mutated to untraced value")
                writebacks.append((tensor, new_sym))
                seen.add(id(tensor))
        for aid, (tensor, sym) in list(self._captures.items()):
            cur = id(tensor._value)
            if cur != aid and id(tensor) not in seen:
                new_sym = self._sym_of.get(cur)
                if new_sym is None:
                    raise TraceInvalid("capture mutated to untraced value")
                writebacks.append((tensor, new_sym))
                seen.add(id(tensor))

        captures = [(t, sym) for (t, sym) in self._captures.values()]
        input_syms = [sym for (_, sym, _) in self._inputs]
        return StatementIR(input_syms, captures, self.statements,
                           len(self._rng_slots), out_syms, tree,
                           out_consts, writebacks)


def build_replay(ir: StatementIR) -> Callable:
    """Compile the IR into a pure function
    ``replay(base_key, *capture_arrays, *input_arrays) -> tuple`` suitable
    for jax.jit + apply_op dispatch (grads flow to captures and inputs)."""
    from ...core.dispatch import _amp_cast

    n_cap = len(ir.captures)
    cap_syms = [sym for (_, sym) in ir.captures]
    tensor_out_syms = [s for s in ir.out_syms if s is not None]
    wb_syms = [sym for (_, sym) in ir.writebacks]

    def replay(base_key, *arrays):
        env: Dict[int, Any] = {}
        for sym, arr in zip(cap_syms, arrays[:n_cap]):
            env[sym] = arr
        for sym, arr in zip(ir.input_syms, arrays[n_cap:]):
            env[sym] = arr
        rng = [jax.random.fold_in(base_key, i) for i in range(ir.n_rng)]
        for st in ir.statements:
            vals = []
            for kind, v in st.arg_spec:
                if kind == "s":
                    vals.append(env[v])
                elif kind == "r":
                    vals.append(rng[v])
                else:
                    vals.append(v)
            if st.cast_to is not None:
                vals = [_amp_cast(v, st.cast_to) for v in vals]
            out = st.fn(*vals, **st.kwargs)
            outs = out if isinstance(out, tuple) else (out,)
            for sym, v in zip(st.out_syms, outs):
                env[sym] = v
        return tuple(env[s] for s in tensor_out_syms + wb_syms)

    return replay
