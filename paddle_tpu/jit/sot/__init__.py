"""SOT — bytecode-level symbolic trace for to_static.

Capability parity with the reference's default to_static mode
(reference: python/paddle/jit/sot/ — eval-frame hook paddle/fluid/pybind/
eval_frame.c, OpcodeExecutor opcode_translator/executor/opcode_executor.py:1473,
StatementIR symbolic/statement_ir.py, guards + graph-break fallback
eval_frame_callback.py:52).

TPU-native design — trace-by-execution over the dispatch choke point:

- The first call of a traced function is interpreted bytecode-by-bytecode
  by :class:`OpcodeExecutor` (opcode_executor.py) with *real* values: every
  framework op executes eagerly (so the first call is exactly an eager
  call, side effects included) while the dispatch choke point
  (core/dispatch.py `_sot_recorder`) records each op into a
  :class:`StatementIR`.
- If the frame finishes without a graph break, the StatementIR is compiled
  into one `jax.jit` program (the analog of the reference's compiled
  partial program) and cached under input guards; subsequent calls run the
  single XLA module through the autograd tape.
- Graph breaks (data-dependent `if`/`while` on tensor values, host
  materialization like `.item()`/`print`, unsupported opcodes, explicit
  seeds) mark the frame eager-only — the honest fallback; unlike CUDA
  eager, XLA still compiles each op, so fallback stays correct and usable.
- Randomness: ops pass drawn PRNG keys as visible statement args; the
  recorder replaces them with fold-ins of a fresh per-call base key, so
  compiled dropout re-randomizes without retracing.  A key drawn but never
  seen among statement args poisons the trace (safety net).

Whole-frame fallback replaces the reference's resume-function machinery:
under XLA there is no perf cliff between "partially compiled" and "eager",
so correctness-preserving skip-frame is the right TPU trade.
"""
from .statement_ir import Statement, StatementIR, Recorder
from .opcode_executor import OpcodeExecutor, scan_code, GraphBreakReason
from .translate import SOTFunction, symbolic_translate

__all__ = [
    "Statement", "StatementIR", "Recorder", "OpcodeExecutor",
    "scan_code", "GraphBreakReason", "SOTFunction", "symbolic_translate",
]
