"""paddle_tpu.jit — dynamic-to-static compilation.

Parity: python/paddle/jit/ (reference — @to_static api.py:171, AST
transformer pipeline dy2static/, partial_program run_program op
paddle/fluid/eager/to_static/run_program_op_node.h, jit.save/load +
TranslatedLayer translated_layer.py).

TPU-native design (SURVEY.md §7): the trace front-end is JAX itself — a
to_static function traces the Python callable once per input signature into
a jaxpr → StableHLO executable (the CINN/PIR lowering collapses into XLA).
The compiled region participates in the eager tape as ONE GradNode whose VJP
is the XLA-compiled backward (exactly the reference's run_program-op-as-
GradNode design, §3.4) — so eager and compiled code mix freely.
jit.save serializes the StableHLO executable + params; jit.load returns a
TranslatedLayer.
"""
from .api import to_static, StaticFunction, not_to_static, ignore_module
from .save_load import save, load, TranslatedLayer
from .api import enable_to_static
from .convert_ops import bounded_loops
from .serving_step import DecodeStep

__all__ = ["to_static", "StaticFunction", "save", "load", "TranslatedLayer",
           "bounded_loops",
           "not_to_static", "enable_to_static", "DecodeStep"]


# -- translator logging knobs (parity: paddle/jit/dy2static/logging_utils
# set_code_level/set_verbosity).  The SOT/AST translator honors these via
# paddle_tpu.jit.sot logging.
_TRANSLATOR_LOG = {"code_level": -1, "verbosity": 0}


def set_code_level(level=100, also_to_stdout=False):
    """Parity: paddle.jit.set_code_level — log the transformed code at
    ``level`` (our translator logs captured StatementIR instead of AST
    stages)."""
    _TRANSLATOR_LOG["code_level"] = int(level)
    _TRANSLATOR_LOG["also_to_stdout"] = bool(also_to_stdout)


def set_verbosity(level=0, also_to_stdout=False):
    """Parity: paddle.jit.set_verbosity."""
    _TRANSLATOR_LOG["verbosity"] = int(level)
    _TRANSLATOR_LOG["also_to_stdout"] = bool(also_to_stdout)


__all__ += ["set_code_level", "set_verbosity",
            "LlamaLayerwiseTrainStep"]


def __getattr__(name):
    # lazy: layerwise pulls the llama model + pallas kernels, which
    # plain to_static/save/load users should not pay for at import
    if name == "LlamaLayerwiseTrainStep":
        from .layerwise import LlamaLayerwiseTrainStep
        return LlamaLayerwiseTrainStep
    raise AttributeError(name)
