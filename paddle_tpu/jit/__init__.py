"""paddle_tpu.jit — dynamic-to-static compilation.

Parity: python/paddle/jit/ (reference — @to_static api.py:171, AST
transformer pipeline dy2static/, partial_program run_program op
paddle/fluid/eager/to_static/run_program_op_node.h, jit.save/load +
TranslatedLayer translated_layer.py).

TPU-native design (SURVEY.md §7): the trace front-end is JAX itself — a
to_static function traces the Python callable once per input signature into
a jaxpr → StableHLO executable (the CINN/PIR lowering collapses into XLA).
The compiled region participates in the eager tape as ONE GradNode whose VJP
is the XLA-compiled backward (exactly the reference's run_program-op-as-
GradNode design, §3.4) — so eager and compiled code mix freely.
jit.save serializes the StableHLO executable + params; jit.load returns a
TranslatedLayer.
"""
from .api import to_static, StaticFunction, not_to_static, ignore_module
from .save_load import save, load, TranslatedLayer
from .api import enable_to_static
from .convert_ops import bounded_loops

__all__ = ["to_static", "StaticFunction", "save", "load", "TranslatedLayer",
           "bounded_loops",
           "not_to_static", "enable_to_static"]
