"""Runtime converters for dy2static control flow.

Parity: python/paddle/jit/dy2static/convert_operators.py (reference —
convert_ifelse :403, convert_while_loop :103, convert_logical_and :226).
The AST transformer (transformers.py here) rewrites python ``if`` /
``while`` / ``for range`` whose predicates may be traced tensors into
calls to these converters, which dispatch:

- python value predicate  -> plain python control flow (zero overhead)
- traced Tensor predicate -> ``lax.cond`` / ``lax.while_loop`` so the
  construct compiles into the XLA module (no unrolling, no host sync)
"""
from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor


class _Undef:
    """Sentinel for names not bound in the enclosing scope (the analog of
    the reference's UndefinedVar)."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"


UNDEF = _Undef()


def try_read(thunk: Callable):
    """Evaluate ``lambda: name`` against the enclosing scope; UNDEF when
    the name is not bound yet (used for branch-fn argument defaults)."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UNDEF


def _is_traced(x) -> bool:
    if isinstance(x, Tensor):
        x = x._value
    return isinstance(x, jax.core.Tracer)


def _pred_value(pred):
    if isinstance(pred, Tensor):
        return pred._value
    return pred


def _to_vals(tree):
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap_like(vals, like):
    def one(v, l):
        return Tensor._from_value(v) if isinstance(l, Tensor) else v
    return jax.tree_util.tree_map(one, vals, like,
                                  is_leaf=lambda x: isinstance(x, Tensor))


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable):
    """``if`` whose predicate may be a traced tensor.

    Python-value predicates run one branch eagerly; traced predicates
    compile both branches under ``lax.cond`` (branch outputs must match in
    structure/shape/dtype, like the reference's select_input check)."""
    p = _pred_value(pred)
    if not isinstance(p, (jax.Array, jax.core.Tracer)) or not _is_traced(p):
        return true_fn() if bool(np.asarray(p)) else false_fn()

    t_out = true_fn()
    f_out = false_fn()
    t_vals = _to_vals(t_out)
    f_vals = _to_vals(f_out)
    # harmonize weakly-typed leaves so cond branches typecheck
    try:
        out_vals = lax.cond(jnp.reshape(p, ()).astype(bool),
                            lambda: t_vals, lambda: f_vals)
    except TypeError as e:
        raise TypeError(
            "to_static: both branches of a tensor-predicate `if` must "
            f"produce matching shapes/dtypes/structures: {e}") from e
    return _wrap_like(out_vals, t_out)


# Thread-local bound for traced while-loops: inside ``bounded_loops(n)``
# a tensor-predicate while lowers to a masked lax.scan of length n, which
# XLA CAN reverse-differentiate (lax.while_loop cannot).  The TPU-native
# answer to the reference's differentiable static While op
# (python/paddle/static/nn/control_flow.py While).
import threading as _threading

_LOOP_BOUND = _threading.local()


class bounded_loops:
    """Context manager: declare a static trip-count bound for traced
    tensor-``while`` loops so they become reverse-differentiable.

        with paddle_tpu.jit.bounded_loops(64):
            loss = traced_fn_with_tensor_while(x)
        loss.backward()           # works: the loop is a masked scan
    """

    def __init__(self, max_iters: int):
        self._n = int(max_iters)

    def __enter__(self):
        self._prev = getattr(_LOOP_BOUND, "n", None)
        _LOOP_BOUND.n = self._n
        return self

    def __exit__(self, *exc):
        _LOOP_BOUND.n = self._prev
        return False


def _bounded_while(cond, body, init_vals, max_iters: int):
    """while as a masked scan: runs exactly ``max_iters`` (masked) steps,
    so reverse-mode AD applies.  Semantically equal to the while loop
    whenever the true trip count <= max_iters."""
    def step(carry, _):
        vals, active = carry
        act = active & cond(vals)
        new = body(vals)
        vals = tuple(jnp.where(act, n, v) for n, v in zip(new, vals))
        return (vals, act), None

    (out_vals, _), _ = lax.scan(step, (tuple(init_vals),
                                       jnp.asarray(True)), None,
                                length=max_iters)
    return out_vals


def convert_while_loop(cond_fn: Callable, body_fn: Callable,
                       loop_vars: Tuple, max_iters: int = None):
    """``while`` whose condition may be a traced tensor.

    Loop-carried variables are exactly the names the transformer passed;
    under trace they become the ``lax.while_loop`` carry (shapes must be
    loop-invariant).  With ``max_iters`` (explicit, or ambient via
    :class:`bounded_loops`) a traced loop lowers to a masked ``lax.scan``
    instead — differentiable in reverse mode."""
    def _norm(out):
        # body may return list (paddle convention), tuple, or scalar
        if isinstance(out, list):
            return tuple(out)
        if not isinstance(out, tuple):
            return (out,)
        return out

    first = cond_fn(*loop_vars)
    if not _is_traced(first):
        # eager python loop (condition re-evaluated on real values).
        # Only an EXPLICIT max_iters truncates here (matching the traced
        # masked scan); the ambient bounded_loops bound exists purely to
        # make traced loops differentiable and must not change eager
        # semantics.
        it = 0
        while bool(np.asarray(_pred_value(first))):
            if max_iters is not None and it >= int(max_iters):
                break
            loop_vars = _norm(body_fn(*loop_vars))
            first = cond_fn(*loop_vars)
            it += 1
        return loop_vars

    if max_iters is None:
        max_iters = getattr(_LOOP_BOUND, "n", None)

    template = loop_vars

    def cond(vals):
        vars_ = _wrap_like(vals, template)
        return jnp.reshape(_pred_value(cond_fn(*vars_)), ()).astype(bool)

    def body(vals):
        vars_ = _wrap_like(vals, template)
        return _to_vals(_norm(body_fn(*vars_)))

    if max_iters is not None:
        out_vals = _bounded_while(cond, body, _to_vals(loop_vars),
                                  int(max_iters))
    else:
        out_vals = lax.while_loop(cond, body, _to_vals(loop_vars))
    return _wrap_like(out_vals, template)


def convert_for_range(start, stop, step, body_fn: Callable,
                      loop_vars: Tuple):
    """``for i in range(...)`` with possibly-traced bounds: lowered to a
    while loop with (i, *loop_vars) carry."""
    def cond_fn(i, *vars_):
        s = _pred_value(step)
        return convert_logical_cmp(i, stop, s)

    def body(i, *vars_):
        out = body_fn(i, *vars_)
        if not isinstance(out, tuple):
            out = (out,)
        return (i + step,) + out

    init = (start,) + tuple(loop_vars)
    out = convert_while_loop(cond_fn, body, init)
    # python leaves the index at its last yielded value after the loop;
    # the carry ends one step past it (start - step if the loop never ran)
    return (out[0] - step,) + tuple(out[1:])


def convert_logical_cmp(i, stop, step):
    sv = step._value if isinstance(step, Tensor) else step
    if _is_traced(sv) or _is_traced(i) or _is_traced(stop):
        iv = _pred_value(i)
        st = _pred_value(stop)
        s = _pred_value(step)
        return jnp.where(s > 0, iv < st, iv > st)
    return (i < stop) if step > 0 else (i > stop)


def convert_logical_and(x_fn: Callable, y_fn: Callable):
    """Short-circuiting ``and`` (reference convert_logical_and :226)."""
    x = x_fn()
    if not _is_traced(_pred_value(x)):
        if not bool(np.asarray(_pred_value(x))):
            return x
        return y_fn()
    y = y_fn()
    return Tensor._from_value(
        jnp.logical_and(jnp.reshape(_pred_value(x), ()),
                        jnp.reshape(_pred_value(y), ())))


def convert_logical_or(x_fn: Callable, y_fn: Callable):
    x = x_fn()
    if not _is_traced(_pred_value(x)):
        if bool(np.asarray(_pred_value(x))):
            return x
        return y_fn()
    y = y_fn()
    return Tensor._from_value(
        jnp.logical_or(jnp.reshape(_pred_value(x), ()),
                       jnp.reshape(_pred_value(y), ())))


def convert_logical_not(x):
    v = _pred_value(x)
    if _is_traced(v):
        return Tensor._from_value(jnp.logical_not(jnp.reshape(v, ())))
    return not bool(np.asarray(v))


_CALL_CACHE: dict = {}


def convert_call(fn):
    """Recursively convert called user functions (reference convert_call,
    dy2static/convert_call_func.py:108): plain python functions defined
    outside this framework / jax / numpy get the same AST transforms, so
    control flow in helpers compiles too.  Everything else passes through
    untouched."""
    from .transformers import convert_function
    import types

    if not isinstance(fn, types.FunctionType):
        return fn
    mod = getattr(fn, "__module__", "") or ""
    if mod.split(".")[0] in ("paddle_tpu", "jax", "jaxlib", "numpy",
                             "builtins", "torch", "math", "functools"):
        return fn
    if getattr(fn, "_not_to_static", False) or \
            getattr(fn, "__pt_converted__", False):
        return fn
    cached = _CALL_CACHE.get(fn)
    if cached is None:
        try:
            cached = convert_function(fn)
        except Exception:
            cached = fn
        _CALL_CACHE[fn] = cached
    return cached
