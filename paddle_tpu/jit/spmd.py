"""Shared SPMD machinery for the fused compiled steps.

One home for everything both the training step (``jit/train_step.py``,
ZeRO-1/2 weight-update sharding) and the serving steps
(``jit/serving_step.py``, tensor-parallel multi-chip decode/prefill)
need to agree on: mesh/axis resolution, the :class:`ShardingConfig`
the callers hand in, the canonical per-weight-family
:class:`SpecLayout` (the ``PartitionSpec`` table tensor-parallel
serving shards the llama weight families by), and the small traced
helpers (vocab-parallel embedding, logits all-gather) the sharded
serving bodies compose under ``shard_map``.

Weight-family layout (Megatron-style tensor parallelism over a ``tp``
mesh axis; Linear weights are ``[in, out]``):

====================  =======================  =========================
family                spec                     collective it implies
====================  =======================  =========================
embed_tokens.weight   P(tp, None)  vocab-row   one psum after the masked
                                               local lookup (exact: every
                                               token's row lives on ONE
                                               chip, the others add 0)
q/k/v_proj.weight     P(None, tp)  head-col    none (activations stay
                                               replicated; outputs are
                                               this chip's head shard)
o_proj.weight         P(tp, None)  head-row    one psum per layer
gate/up_proj.weight   P(None, tp)  ffn-col     none
down_proj.weight      P(tp, None)  ffn-row     one psum per layer
lm_head.weight        P(None, tp)  vocab-col   one all-gather over the
                                               vocab shards (exact)
norms / biases(1-D    P() replicated           none
  except qkv bias)
KV page pools         P(None, None, tp, None)  none — each chip's paged
                                               attention sees only its
                                               kv-head shard of every
                                               page
====================  =======================  =========================

So one fused serving step pays: 1 embedding psum + 2 psums per
transformer layer (attention out, MLP out) + 1 logits all-gather —
"one collective per layer boundary", the pattern EQuARX
(arXiv:2506.17615) quantizes.  The psums split a contraction, so
activations agree with the single-chip step to float addition order
(ULPs); the embedding psum and logits all-gather are bit-exact.  The
parity contract is therefore on the sampled TOKENS, which the serving
benches gate byte-identically.

Stochastic sampling under tp (round 14) adds NO collective: the
``ops/sampling`` epilogue runs AFTER the exact logits all-gather, on
replicated logits with replicated knob/seed operands, and the
counter-based threefry draw is pure deterministic math — every chip
computes the identical token, byte-equal to the single-chip sampled
engine (gated in tests).  ``collective_bytes`` is therefore unchanged
by sampling.  Speculative verification stays single-chip for now (the
draft engine is unsharded); engines reject ``draft_model + mesh`` at
construction.

SNIPPETS.md [3] ``SpecLayout`` (fsdp×tp, MaxText-style) is the exemplar
this table specializes: serving has no fsdp axis (weights are read-only
— replicating them across an fsdp axis buys nothing per step), so every
family collapses to its tp entry.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ShardingConfig", "SpecLayout", "TPContext",
           "resolve_mesh_axis", "llama_param_specs",
           "validate_tp_serving", "tp_mesh", "tp_serving_context",
           "tp_embed", "tp_gather_logits", "tp_gather_logits_q8",
           "shard_arrays"]

P = PartitionSpec


class ShardingConfig:
    """Sharded-step config shared by :class:`~.train_step.TrainStep`
    (ZeRO weight-update sharding over a data-parallel axis) and the
    serving steps (tensor parallelism over a ``tp`` axis).

    stage: ZeRO stage for the TRAIN step — 1 (ZeRO-1 / 'os'): full-
        gradient all-reduce, optimizer state + weight update sharded
        over the dp axis; 2 (ZeRO-2 / 'os_g'): the grad sync itself
        becomes one reduce-scatter per coalesced bucket.  Serving
        ignores it.
    degree: number of shards; -1 infers the mesh axis size (a positive
        value must equal it — sub-axis sharding would need a mesh
        reshape).
    axis: mesh axis name to shard over ('dp' on the Engine mesh for
        training, 'tp' for tensor-parallel serving).
    bucket_mb: stage-2 coalesced reduce-scatter bucket size (train
        only).
    loss_reduction: how per-replica losses/grads combine (train only).
    """

    def __init__(self, stage: int = 1, degree: int = -1, axis: str = "dp",
                 bucket_mb: float = 25.0, loss_reduction: str = "mean"):
        if int(stage) not in (1, 2):
            raise ValueError(
                f"ShardingConfig stage must be 1 (os) or 2 (os_g), got "
                f"{stage!r}; stage 3 stores the params themselves sharded "
                f"(GroupShardedStage3)")
        if loss_reduction not in ("mean", "sum"):
            raise ValueError(
                f"loss_reduction must be 'mean' or 'sum', got "
                f"{loss_reduction!r}")
        self.stage = int(stage)
        self.degree = int(degree)
        self.axis = axis
        self.bucket_mb = float(bucket_mb)
        self.loss_reduction = loss_reduction

    def __repr__(self):
        return (f"ShardingConfig(stage={self.stage}, degree={self.degree}, "
                f"axis={self.axis!r}, bucket_mb={self.bucket_mb}, "
                f"loss_reduction={self.loss_reduction!r})")


def resolve_mesh_axis(mesh, axis: str,
                      degree: int = -1,
                      candidates: Sequence[str] = ("dp", "sharding",
                                                   "data"),
                      ) -> Tuple[Mesh, str, int]:
    """Unwrap ``mesh`` to a jax Mesh and pick the axis to shard over.

    ``axis`` wins when present; otherwise the first name in
    ``candidates`` that exists on the mesh with size > 1.  ``degree``
    must equal the axis size or be -1 (infer).  Returns
    ``(jax_mesh, axis_name, axis_size)`` — size 1 means "degenerate:
    run the unsharded step".
    """
    from ..distributed.process_mesh import as_jax_mesh
    if mesh is None:
        raise ValueError("ShardingConfig requires a mesh")
    jmesh = as_jax_mesh(mesh)
    if axis not in jmesh.axis_names:
        axis = next((a for a in candidates
                     if a in jmesh.axis_names and jmesh.shape[a] > 1),
                    None)
        if axis is None:
            raise ValueError(
                f"no shardable axis on mesh {tuple(jmesh.axis_names)} "
                f"(wanted one of {tuple(candidates)})")
    deg = jmesh.shape[axis]
    if degree not in (-1, deg):
        raise ValueError(
            f"sharding degree {degree} must equal the '{axis}' axis "
            f"size {deg} (or -1 to infer)")
    return jmesh, axis, deg


def tp_mesh(tp: int, axis: str = "tp"):
    """A 1-D ``tp``-wide ProcessMesh over the first ``tp`` devices —
    the standard serving mesh (benches, tests, single-host engines).
    Reuse the train-step mesh instead when co-located (any mesh with a
    ``tp`` axis resolves)."""
    from ..distributed.process_mesh import ProcessMesh
    n = jax.device_count()
    if tp > n:
        raise ValueError(
            f"tp={tp} exceeds the {n} visible devices; for CPU dryruns "
            f"call paddle_tpu.testing.dryrun.force_cpu_devices first")
    return ProcessMesh(shape=[tp], dim_names=[axis])


# ---------------------------------------------------------------------------
# canonical per-weight-family specs
# ---------------------------------------------------------------------------
class SpecLayout:
    """Canonical PartitionSpecs per llama weight family for
    tensor-parallel serving (see the module docstring's table)."""

    def __init__(self, tp_axis: str = "tp"):
        self.tp_axis = tp_axis

    def embeddings(self) -> PartitionSpec:
        """[V, h] vocab-row sharded: masked local lookup + one exact
        psum (Megatron vocab-parallel embedding)."""
        return P(self.tp_axis, None)

    def qkv_projection(self) -> PartitionSpec:
        """[h, H*D] column (head) sharded: each chip projects only its
        own query/kv heads."""
        return P(None, self.tp_axis)

    def qkv_bias(self) -> PartitionSpec:
        """[H*D] follows its projection's column shard."""
        return P(self.tp_axis)

    def attn_output(self) -> PartitionSpec:
        """[H*D, h] row sharded — the per-layer psum boundary."""
        return P(self.tp_axis, None)

    def ffn_up(self) -> PartitionSpec:
        """gate/up [h, I] column sharded (SwiGLU is elementwise on the
        shard)."""
        return P(None, self.tp_axis)

    def ffn_down(self) -> PartitionSpec:
        """down [I, h] row sharded — the other per-layer psum."""
        return P(self.tp_axis, None)

    def lm_head(self) -> PartitionSpec:
        """[h, V] vocab-column sharded: local [*, V/tp] logits, one
        exact all-gather before the on-device argmax."""
        return P(None, self.tp_axis)

    def replicated(self) -> PartitionSpec:
        return P()

    def kv_pool(self) -> PartitionSpec:
        """[phys_pages, block_size, Hkv, D] sharded over kv heads: each
        chip's paged-attention launch sees only its head shard of every
        page — per-chip pool HBM is exactly 1/tp."""
        return P(None, None, self.tp_axis, None)

    def kv_scale(self) -> PartitionSpec:
        """An int8 pool's [phys_pages, Hkv] absmax tables follow the
        pool's kv-head shard (quantize/dequantize/rescale are all
        head-local math)."""
        return P(None, self.tp_axis)

    def col_weight_scale(self) -> PartitionSpec:
        """Per-output-channel scale vector of a COLUMN-sharded weight
        (qkv / gate / up / lm_head): the channel axis IS the sharded
        output axis, so the scales shard with it."""
        return P(self.tp_axis)

    def row_weight_scale(self) -> PartitionSpec:
        """Per-output-channel scale vector of a ROW-sharded weight
        (o_proj / down): the output axis is the replicated hidden dim,
        so every chip holds the full vector."""
        return P()


def llama_param_specs(keys: Iterable[str],
                      layout: Optional[SpecLayout] = None,
                      ) -> Dict[str, PartitionSpec]:
    """Classify llama state-dict keys into the canonical family specs.

    Unknown families (norm weights, scalars) stay replicated — correct
    for anything whose math runs identically on every chip.

    Serving-PTQ trees (``quantization.functional.quantize_param_tree``)
    interleave per-channel scale vectors under ``<param>::scale`` keys;
    those classify by their BASE weight's family — sharded with the
    output axis for column-sharded weights (qkv / gate / up / lm_head),
    replicated for row-sharded ones (o_proj / down) whose output axis
    is the hidden dim.  int8 weights themselves keep their family's
    2-D spec (quantization changes the dtype, not the layout).
    """
    from ..quantization.functional import WEIGHT_SCALE_SUFFIX
    layout = layout or SpecLayout()
    specs: Dict[str, PartitionSpec] = {}
    for k in keys:
        if k.endswith(WEIGHT_SCALE_SUFFIX):
            base = k[:-len(WEIGHT_SCALE_SUFFIX)]
            if any(p in base for p in ("q_proj", "k_proj", "v_proj",
                                       "gate_proj", "up_proj",
                                       "lm_head")):
                specs[k] = layout.col_weight_scale()
            elif "o_proj" in base or "down_proj" in base:
                specs[k] = layout.row_weight_scale()
            else:
                specs[k] = layout.replicated()
        elif "embed_tokens" in k:
            specs[k] = layout.embeddings()
        elif any(p in k for p in ("q_proj", "k_proj", "v_proj")):
            specs[k] = layout.qkv_bias() if k.endswith("bias") \
                else layout.qkv_projection()
        elif "o_proj" in k:
            specs[k] = layout.attn_output()
        elif "gate_proj" in k or "up_proj" in k:
            specs[k] = layout.ffn_up()
        elif "down_proj" in k:
            specs[k] = layout.ffn_down()
        elif "lm_head" in k:
            specs[k] = layout.lm_head()
        else:
            specs[k] = layout.replicated()
    return specs


def shard_arrays(arrays: Dict[str, jnp.ndarray], mesh: Mesh,
                 specs: Dict[str, PartitionSpec]) -> Dict[str, jnp.ndarray]:
    """device_put each array with its spec's NamedSharding (the one-time
    placement at sharded-step init; params never cross the link again)."""
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in arrays.items()}


def validate_tp_serving(cfg, degree: int, pool_kv_heads: Optional[int]
                        = None) -> None:
    """Every divisibility constraint tensor-parallel serving needs,
    checked at ENGINE CONSTRUCTION with one actionable message —
    instead of a shard_map shape failure deep inside tracing."""
    if degree <= 1:
        return
    problems = []
    for name, val in (("num_attention_heads", cfg.num_attention_heads),
                      ("num_key_value_heads", cfg.num_key_value_heads),
                      ("vocab_size", cfg.vocab_size),
                      ("intermediate_size", cfg.intermediate_size)):
        if val % degree:
            problems.append(f"{name}={val}")
    if pool_kv_heads is not None \
            and pool_kv_heads != cfg.num_key_value_heads:
        problems.append(
            f"KV page pool has {pool_kv_heads} kv heads but the model "
            f"config says {cfg.num_key_value_heads}")
    if problems:
        raise ValueError(
            "tensor-parallel serving with tp=%d requires every sharded "
            "dimension to divide by tp; violated: %s.  Pick a tp that "
            "divides the head/vocab/ffn dims (or pad the model)."
            % (degree, ", ".join(problems)))


class TPContext:
    """Resolved tensor-parallel serving context, shared by every
    serving step of one engine: the jax mesh, the axis name/degree, the
    spec layout, the per-param specs, and the ONE placed copy of the
    sharded parameters (placed lazily on first use; params are
    read-only in serving, so they never cross the host link again)."""

    def __init__(self, mesh: Mesh, axis: str, degree: int,
                 layout: SpecLayout, specs: Dict[str, PartitionSpec]):
        self.mesh = mesh
        self.axis = axis
        self.degree = degree
        self.layout = layout
        self.specs = specs
        self._placed: Optional[Dict[str, jnp.ndarray]] = None
        self._placed_src: Dict[str, jnp.ndarray] = {}

    def place_params(self, arrays: Dict[str, jnp.ndarray]
                     ) -> Dict[str, jnp.ndarray]:
        """Sharded placement with staleness tracking: jax arrays are
        immutable, so a weight update (checkpoint load, requantize)
        rebinds the source array — detected per key by identity against
        a HELD reference (a bare id() could be fooled by address reuse
        after the old array is freed) and only the changed params are
        re-placed.  Steady-state serving pays an `is` comparison per
        param, never a transfer."""
        if self._placed is None:
            self._placed = shard_arrays(
                arrays, self.mesh, {k: self.specs[k] for k in arrays})
            self._placed_src = dict(arrays)
            return self._placed
        for k, v in arrays.items():
            if self._placed_src.get(k) is not v:
                self._placed[k] = jax.device_put(
                    v, NamedSharding(self.mesh, self.specs[k]))
                self._placed_src[k] = v
        return self._placed

    def collective_bytes(self, cfg, n_tokens: int,
                         n_gather_rows: int,
                         quant_gather: bool = False) -> Dict[str, int]:
        """Per-chip collective payload of ONE sharded serving dispatch:
        (1 + 2L) psums of [n_tokens, hidden] (embedding + the two
        per-layer boundaries) and one all-gather of the
        [n_gather_rows, vocab/tp] logits shard — the static-per-shape
        accounting behind ``serving_tp_collective_bytes_total``.

        ``quant_gather=True`` accounts the EQuARX-style int8 logits
        all-gather (``tp_gather_logits_q8``): one byte per logit plus
        the 4-byte per-shard scale — the payload the quantized
        collective actually moves (reported under
        ``serving_quant_collective_bytes_total`` too)."""
        item = 2 if cfg.dtype == "bfloat16" else 4
        shard = n_gather_rows * (cfg.vocab_size // self.degree)
        return {
            "psum": (2 * cfg.num_hidden_layers + 1) * n_tokens
            * cfg.hidden_size * item,
            "all_gather": shard + 4 if quant_gather else shard * item,
        }

    def pool_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.layout.kv_pool())

    def kv_scale_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.layout.kv_scale())

    def named(self, spec_tree):
        """PartitionSpec tree -> NamedSharding tree on this mesh (jit
        in_shardings/out_shardings from shard_map in_specs/out_specs)."""
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), spec_tree,
            is_leaf=lambda s: isinstance(s, PartitionSpec))

    def __repr__(self):
        return (f"TPContext(axis={self.axis!r}, degree={self.degree}, "
                f"mesh={tuple(self.mesh.shape.items())})")


def tp_serving_context(model, mesh, sharding: Optional[ShardingConfig]
                       = None) -> Optional[TPContext]:
    """Resolve engine-construction arguments into a :class:`TPContext`
    (or None when the axis degenerates to 1 — run the single-chip
    step).  Validates every divisibility constraint up front."""
    cfg = sharding or ShardingConfig(axis="tp")
    jmesh, axis, deg = resolve_mesh_axis(
        mesh, cfg.axis, cfg.degree, candidates=("tp", "model", "mp"))
    if deg <= 1:
        return None
    validate_tp_serving(model.config, deg)
    layout = SpecLayout(tp_axis=axis)
    specs = llama_param_specs(model.state_dict().keys(), layout)
    return TPContext(jmesh, axis, deg, layout, specs)


# ---------------------------------------------------------------------------
# traced helpers (composed inside the shard_map'd serving bodies)
# ---------------------------------------------------------------------------
def tp_embed(table_local, tokens, axis: str):
    """Vocab-parallel embedding lookup (Megatron): ``table_local`` is
    this chip's [V/tp, h] row shard; returns the REPLICATED [..., h]
    embeddings.  Exact: each token's row lives on exactly one chip, so
    the psum adds zeros from every other chip — bit-identical to the
    single-chip gather."""
    vs = table_local.shape[0]
    start = jax.lax.axis_index(axis).astype(jnp.int32) * vs
    local = tokens.astype(jnp.int32) - start
    ok = (local >= 0) & (local < vs)
    e = table_local[jnp.clip(local, 0, vs - 1)]
    e = jnp.where(ok[..., None], e, jnp.zeros((), e.dtype))
    return jax.lax.psum(e, axis)


def tp_gather_logits(logits_local, axis: str):
    """All-gather the [*, V/tp] vocab-sharded logits into the
    replicated [*, V] block (exact — pure concatenation in chip order,
    which IS vocab order under the column shard), so the on-device
    argmax sees the same values as the single-chip step."""
    return jax.lax.all_gather(logits_local, axis,
                              axis=logits_local.ndim - 1, tiled=True)


def tp_gather_logits_q8(logits_local, axis: str):
    """EQuARX-style (arXiv:2506.17615) quantized logits all-gather:
    each chip quantizes its [*, V/tp] vocab shard to symmetric int8
    with ONE per-shard absmax scale, the gather moves int8 codes (+ a
    4-byte scale each) instead of fp words — ~4× (fp32) / ~2× (bf16)
    less interconnect payload — and every chip dequantizes each shard
    with its own gathered scale before the argmax.

    NOT exact: two logits within ``absmax/127`` of each other can swap
    order after the round trip, so engines enable this behind a
    measured token-match-rate gate (a tolerance gate, not byte parity
    — the serving quantization bench reports the rate per workload).
    """
    from ..quantization.functional import (dequantize_symmetric,
                                           quantize_symmetric)
    x = logits_local.astype(jnp.float32)
    s = jnp.max(jnp.abs(x))                              # per-shard
    q = quantize_symmetric(x, s).astype(jnp.int8)
    gq = jax.lax.all_gather(q, axis, axis=q.ndim - 1, tiled=True)
    gs = jax.lax.all_gather(s, axis)                     # [tp]
    tp = gs.shape[0]
    lead, V = gq.shape[:-1], gq.shape[-1]
    out = dequantize_symmetric(gq.reshape(lead + (tp, V // tp)),
                               gs[:, None])
    return out.reshape(lead + (V,)).astype(logits_local.dtype)
