"""Shared SPMD machinery for the fused compiled steps.

One home for everything both the training step (``jit/train_step.py``,
ZeRO-1/2 weight-update sharding) and the serving steps
(``jit/serving_step.py``, tensor-parallel multi-chip decode/prefill)
need to agree on: mesh/axis resolution, the :class:`ShardingConfig`
the callers hand in, the canonical per-weight-family
:class:`SpecLayout` (the ``PartitionSpec`` table tensor-parallel
serving shards the llama weight families by), and the small traced
helpers (vocab-parallel embedding, logits all-gather) the sharded
serving bodies compose under ``shard_map``.

Weight-family layout (Megatron-style tensor parallelism over a ``tp``
mesh axis; Linear weights are ``[in, out]``):

====================  =======================  =========================
family                spec                     collective it implies
====================  =======================  =========================
embed_tokens.weight   P(tp, None)  vocab-row   one psum after the masked
                                               local lookup (exact: every
                                               token's row lives on ONE
                                               chip, the others add 0)
q/k/v_proj.weight     P(None, tp)  head-col    none (activations stay
                                               replicated; outputs are
                                               this chip's head shard)
o_proj.weight         P(tp, None)  head-row    one psum per layer
gate/up_proj.weight   P(None, tp)  ffn-col     none
down_proj.weight      P(tp, None)  ffn-row     one psum per layer
lm_head.weight        P(None, tp)  vocab-col   one all-gather over the
                                               vocab shards (exact)
norms / biases(1-D    P() replicated           none
  except qkv bias)
KV page pools         P(None, None, tp, None)  none — each chip's paged
                                               attention sees only its
                                               kv-head shard of every
                                               page
====================  =======================  =========================

So one fused serving step pays: 1 embedding psum + 2 psums per
transformer layer (attention out, MLP out) + 1 logits all-gather —
"one collective per layer boundary", the pattern EQuARX
(arXiv:2506.17615) quantizes.  The psums split a contraction, so
activations agree with the single-chip step to float addition order
(ULPs); the embedding psum and logits all-gather are bit-exact.  The
parity contract is therefore on the sampled TOKENS, which the serving
benches gate byte-identically.

Stochastic sampling under tp (round 14) adds NO collective: the
``ops/sampling`` epilogue runs AFTER the exact logits all-gather, on
replicated logits with replicated knob/seed operands, and the
counter-based threefry draw is pure deterministic math — every chip
computes the identical token, byte-equal to the single-chip sampled
engine (gated in tests).  ``collective_bytes`` is therefore unchanged
by sampling.  Speculative verification stays single-chip for now (the
draft engine is unsharded); engines reject ``draft_model + mesh`` at
construction.

2D mesh (round 21) — fsdp×tp everywhere: the MaxText-style fsdp axis
of SNIPPETS.md [3] now composes with the tp table above instead of
collapsing away.  ``SpecLayout(fsdp_axis=...)`` shards each family's
NON-tp dimension over fsdp, so parameter *storage* is cut by
fsdp·tp per chip (ZeRO-3, the stage arXiv:2004.13336 stops short of)
while tp keeps sharding *compute*:

====================  =============  ==================================
family                1D tp spec     fsdp-composed spec
====================  =============  ==================================
embed_tokens.weight   P(tp, None)    P(tp, fsdp)        [V, h]
q/k/v_proj.weight     P(None, tp)    P(fsdp, tp)        [h, H*D]
o_proj.weight         P(tp, None)    P(tp, fsdp)        [H*D, h]
gate/up_proj.weight   P(None, tp)    P(fsdp, tp)        [h, I]
down_proj.weight      P(tp, None)    P(tp, fsdp)        [I, h]
lm_head.weight        P(None, tp)    P(fsdp, tp)        [h, V]
norms / unknown 1-D   P()            P(fsdp) when dim0 divides, else P()
KV page pools         P(,,tp,)       unchanged (replicated over fsdp)
====================  =============  ==================================

Serving gathers the fsdp shards back per dispatch (ONE tiled
all-gather per fsdp-sharded param, inside the shard_map body — the
payload ``spmd_allgather_bytes_total{site="serving_params"}``
accounts), then runs the unchanged Megatron-tp body; training keeps
params / grads / optimizer state in the fsdp×tp placement end to end
(gather for compute, reduce-scatter of grads back to the shard,
sharded update).  Because BOTH steps store the same placement, a
trained param tree serves with zero re-sharding: ``place_params`` is
buffer-identity on already-placed arrays.  Specs never name a replica
(dp) axis, so a 3D serving mesh ``(dp, fsdp, tp)`` replicates
weights/pools across dp for throughput with no code change.  Dims an
axis does not divide are PRUNED from the spec (storage optimization
degrades, never errors); ``mesh_2d`` builds the canonical mesh.

Expert parallelism (round 24) — the ``ep`` axis shards ONLY the
batched MoE expert banks' E dim (``w_gate/w_up/w_down [E, ., .]`` take
``P(ep, None, None)``, classified by :func:`mixtral_param_specs`); the
router, attention, norms and KV pools never name ``ep``, so they
replicate across it.  The fused MoE FFN inside the serving steps pays
two ``all_to_all`` exchanges (dispatch + combine, the reference's
global_scatter/global_gather pair) plus one token-stripe ``all_gather``
per MoE layer — accounted statically by
:meth:`TPContext.collective_bytes` under the ``ep_all_to_all`` /
``ep_all_gather`` keys.  Per-chip expert-weight HBM is exactly 1/ep.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ShardingConfig", "SpecLayout", "TPContext",
           "resolve_mesh_axis", "llama_param_specs",
           "mixtral_param_specs", "validate_tp_serving",
           "validate_cp_serving", "validate_ep_serving",
           "tp_mesh", "mesh_2d", "cp_mesh", "ep_mesh",
           "tp_serving_context", "tp_embed", "tp_gather_logits",
           "tp_gather_logits_q8", "shard_arrays", "spec_axes",
           "prune_spec_axes", "gather_spec_axes", "fsdp_gather"]

P = PartitionSpec


class ShardingConfig:
    """Sharded-step config shared by :class:`~.train_step.TrainStep`
    (ZeRO weight-update sharding over a data-parallel axis) and the
    serving steps (tensor parallelism over a ``tp`` axis).

    stage: ZeRO stage for the TRAIN step — 1 (ZeRO-1 / 'os'): full-
        gradient all-reduce, optimizer state + weight update sharded
        over the dp axis; 2 (ZeRO-2 / 'os_g'): the grad sync itself
        becomes one reduce-scatter per coalesced bucket.  Serving
        ignores it.
    degree: number of shards; -1 infers the mesh axis size (a positive
        value must equal it — sub-axis sharding would need a mesh
        reshape).
    axis: mesh axis name to shard over ('dp' on the Engine mesh for
        training, 'tp' for tensor-parallel serving).
    bucket_mb: stage-2 coalesced reduce-scatter bucket size (train
        only).
    loss_reduction: how per-replica losses/grads combine (train only).
    """

    def __init__(self, stage: int = 1, degree: int = -1, axis: str = "dp",
                 bucket_mb: float = 25.0, loss_reduction: str = "mean"):
        if int(stage) not in (1, 2):
            raise ValueError(
                f"ShardingConfig stage must be 1 (os) or 2 (os_g), got "
                f"{stage!r}; stage-3 (params themselves sharded) is not a "
                f"stage knob here — pass a mesh with an 'fsdp' axis "
                f"(spmd.mesh_2d) and the fsdp×tp TrainStep stores the "
                f"params ZeRO-3-sharded as its natural layout")
        if loss_reduction not in ("mean", "sum"):
            raise ValueError(
                f"loss_reduction must be 'mean' or 'sum', got "
                f"{loss_reduction!r}")
        self.stage = int(stage)
        self.degree = int(degree)
        self.axis = axis
        self.bucket_mb = float(bucket_mb)
        self.loss_reduction = loss_reduction

    def __repr__(self):
        return (f"ShardingConfig(stage={self.stage}, degree={self.degree}, "
                f"axis={self.axis!r}, bucket_mb={self.bucket_mb}, "
                f"loss_reduction={self.loss_reduction!r})")


def resolve_mesh_axis(mesh, axis: str,
                      degree: int = -1,
                      candidates: Sequence[str] = ("dp", "sharding",
                                                   "data"),
                      ) -> Tuple[Mesh, str, int]:
    """Unwrap ``mesh`` to a jax Mesh and pick the axis to shard over.

    ``axis`` wins when present; otherwise the first name in
    ``candidates`` that exists on the mesh with size > 1.  ``degree``
    must equal the axis size or be -1 (infer).  Returns
    ``(jax_mesh, axis_name, axis_size)`` — size 1 means "degenerate:
    run the unsharded step".
    """
    from ..distributed.process_mesh import as_jax_mesh
    if mesh is None:
        raise ValueError("ShardingConfig requires a mesh")
    jmesh = as_jax_mesh(mesh)
    if axis not in jmesh.axis_names:
        axis = next((a for a in candidates
                     if a in jmesh.axis_names and jmesh.shape[a] > 1),
                    None)
        if axis is None:
            raise ValueError(
                f"no shardable axis on mesh {tuple(jmesh.axis_names)} "
                f"(wanted one of {tuple(candidates)})")
    deg = jmesh.shape[axis]
    if degree not in (-1, deg):
        raise ValueError(
            f"sharding degree {degree} must equal the '{axis}' axis "
            f"size {deg} (or -1 to infer)")
    return jmesh, axis, deg


def tp_mesh(tp: int, axis: str = "tp"):
    """A 1-D ``tp``-wide ProcessMesh over the first ``tp`` devices —
    the standard serving mesh (benches, tests, single-host engines).
    Reuse the train-step mesh instead when co-located (any mesh with a
    ``tp`` axis resolves)."""
    from ..distributed.process_mesh import ProcessMesh
    n = jax.device_count()
    if tp > n:
        raise ValueError(
            f"tp={tp} exceeds the {n} visible devices; for CPU dryruns "
            f"call paddle_tpu.testing.dryrun.force_cpu_devices first")
    return ProcessMesh(shape=[tp], dim_names=[axis])


def mesh_2d(fsdp: int, tp: int, replica: int = 1,
            fsdp_axis: str = "fsdp", tp_axis: str = "tp",
            replica_axis: str = "dp"):
    """The canonical 2D ``(fsdp, tp)`` ProcessMesh over the first
    ``replica*fsdp*tp`` devices — first-class instead of the ad-hoc
    device reshapes tests/benches used to hand-roll.  ``replica > 1``
    prepends a pure data-parallel axis (3D serving mesh: weights and
    KV pools replicate across it because specs never name it; the 2D
    train step treats it as extra batch parallelism)."""
    from ..distributed.process_mesh import ProcessMesh
    need = int(replica) * int(fsdp) * int(tp)
    n = jax.device_count()
    if need > n:
        raise ValueError(
            f"mesh_2d(replica={replica}, fsdp={fsdp}, tp={tp}) needs "
            f"{need} devices but only {n} are visible; for CPU dryruns "
            f"call paddle_tpu.testing.dryrun.force_cpu_devices first")
    if replica > 1:
        return ProcessMesh(shape=[replica, fsdp, tp],
                           dim_names=[replica_axis, fsdp_axis, tp_axis])
    return ProcessMesh(shape=[fsdp, tp], dim_names=[fsdp_axis, tp_axis])


def cp_mesh(cp: int, tp: int = 1, cp_axis: str = "cp",
            tp_axis: str = "tp"):
    """The serving ``(cp, tp)`` ProcessMesh over the first ``cp*tp``
    devices (round 22): ``cp`` stripes the KV pool's slot dimension so
    per-chip pool HBM is 1/cp, ``tp`` shards heads as before.
    ``tp=1`` gives the pure context-parallel mesh — weights replicate
    (no spec names ``cp``), only the pools stripe."""
    from ..distributed.process_mesh import ProcessMesh
    need = int(cp) * int(tp)
    n = jax.device_count()
    if need > n:
        raise ValueError(
            f"cp_mesh(cp={cp}, tp={tp}) needs {need} devices but only "
            f"{n} are visible; for CPU dryruns call "
            f"paddle_tpu.testing.dryrun.force_cpu_devices first")
    if tp > 1:
        return ProcessMesh(shape=[cp, tp], dim_names=[cp_axis, tp_axis])
    return ProcessMesh(shape=[cp], dim_names=[cp_axis])


def ep_mesh(ep: int, tp: int = 1, ep_axis: str = "ep",
            tp_axis: str = "tp"):
    """The serving ``(ep, tp)`` ProcessMesh over the first ``ep*tp``
    devices (round 24): ``ep`` shards the MoE expert banks' E dim so
    per-chip expert-weight HBM is 1/ep, ``tp`` shards heads/vocab as
    before.  ``tp=1`` gives the pure expert-parallel mesh — everything
    except the expert banks replicates (no other spec names ``ep``)."""
    from ..distributed.process_mesh import ProcessMesh
    need = int(ep) * int(tp)
    n = jax.device_count()
    if need > n:
        raise ValueError(
            f"ep_mesh(ep={ep}, tp={tp}) needs {need} devices but only "
            f"{n} are visible; for CPU dryruns call "
            f"paddle_tpu.testing.dryrun.force_cpu_devices first")
    if tp > 1:
        return ProcessMesh(shape=[ep, tp], dim_names=[ep_axis, tp_axis])
    return ProcessMesh(shape=[ep], dim_names=[ep_axis])


# ---------------------------------------------------------------------------
# canonical per-weight-family specs
# ---------------------------------------------------------------------------
class SpecLayout:
    """Canonical PartitionSpecs per llama weight family (see the module
    docstring's tables).  ``tp_axis`` shards compute (Megatron);
    ``fsdp_axis`` (round 21, MaxText-style) additionally shards each
    family's non-tp dimension for ZeRO-3 weight STORAGE.  Either axis
    may be ``None`` — a pure-fsdp layout (tp_axis=None) stores sharded
    weights but runs single-chip-math bodies after the gather."""

    def __init__(self, tp_axis: Optional[str] = "tp",
                 fsdp_axis: Optional[str] = None,
                 cp_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None):
        self.tp_axis = tp_axis
        self.fsdp_axis = fsdp_axis
        # round 22: context-parallel axis — stripes ONLY the KV pool's
        # slot dim (weights never name it, so they replicate across cp)
        self.cp_axis = cp_axis
        # round 24: expert-parallel axis — shards ONLY the batched MoE
        # expert banks' E dim (router/attention/pools never name it)
        self.ep_axis = ep_axis

    def embeddings(self) -> PartitionSpec:
        """[V, h] vocab-row sharded: masked local lookup + one exact
        psum (Megatron vocab-parallel embedding); fsdp on the hidden
        dim."""
        return P(self.tp_axis, self.fsdp_axis)

    def qkv_projection(self) -> PartitionSpec:
        """[h, H*D] column (head) sharded: each chip projects only its
        own query/kv heads; fsdp on the input dim."""
        return P(self.fsdp_axis, self.tp_axis)

    def qkv_bias(self) -> PartitionSpec:
        """[H*D] follows its projection's column shard (the one dim is
        tp's, so no fsdp composition)."""
        return P(self.tp_axis)

    def attn_output(self) -> PartitionSpec:
        """[H*D, h] row sharded — the per-layer psum boundary; fsdp on
        the output dim."""
        return P(self.tp_axis, self.fsdp_axis)

    def ffn_up(self) -> PartitionSpec:
        """gate/up [h, I] column sharded (SwiGLU is elementwise on the
        shard); fsdp on the input dim."""
        return P(self.fsdp_axis, self.tp_axis)

    def ffn_down(self) -> PartitionSpec:
        """down [I, h] row sharded — the other per-layer psum; fsdp on
        the output dim."""
        return P(self.tp_axis, self.fsdp_axis)

    def lm_head(self) -> PartitionSpec:
        """[h, V] vocab-column sharded: local [*, V/tp] logits, one
        exact all-gather before the on-device argmax; fsdp on the
        input dim."""
        return P(self.fsdp_axis, self.tp_axis)

    def replicated(self) -> PartitionSpec:
        return P()

    def fsdp_default(self) -> PartitionSpec:
        """Unknown / 1-D families (norm weights, generic Linear params)
        under an fsdp axis: shard dim0 for the storage win — pruned
        back to replicated when dim0 does not divide (see
        :func:`prune_spec_axes`)."""
        return P(self.fsdp_axis) if self.fsdp_axis else P()

    def kv_pool(self) -> PartitionSpec:
        """[phys_pages, block_size, Hkv, D] sharded over kv heads (tp)
        and — round 22 — striped over the block_size SLOT dim (cp):
        each chip holds slots ``[r*bs/cp, (r+1)*bs/cp)`` of EVERY page
        for its head shard, so per-chip pool HBM is exactly
        1/(cp*tp).  Slot striping keeps the page table, refcounts, COW
        and prefix keys chip-local and identical on every chip."""
        return P(None, self.cp_axis, self.tp_axis, None)

    def kv_scale(self) -> PartitionSpec:
        """An int8 pool's [phys_pages, Hkv] absmax tables follow the
        pool's kv-head shard (quantize/dequantize/rescale are all
        head-local math)."""
        return P(None, self.tp_axis)

    def expert_bank(self) -> PartitionSpec:
        """Batched MoE expert weights ``[E, in, out]``
        (w_gate/w_up/w_down): the E dim shards over ep, so each chip
        stores and runs only its own experts; the in/out dims stay
        whole (the grouped einsums are per-expert dense matmuls)."""
        return P(self.ep_axis, None, None)

    def expert_bank_scale(self) -> PartitionSpec:
        """An int8 expert bank's ``[E, 1, out]`` per-expert-per-channel
        absmax tables follow the bank's E shard (dequant is
        expert-local math)."""
        return P(self.ep_axis, None, None)

    def col_weight_scale(self) -> PartitionSpec:
        """Per-output-channel scale vector of a COLUMN-sharded weight
        (qkv / gate / up / lm_head): the channel axis IS the sharded
        output axis, so the scales shard with it."""
        return P(self.tp_axis)

    def row_weight_scale(self) -> PartitionSpec:
        """Per-output-channel scale vector of a ROW-sharded weight
        (o_proj / down): the output axis is the replicated hidden dim,
        so every chip holds the full vector."""
        return P()


def llama_param_specs(keys: Iterable[str],
                      layout: Optional[SpecLayout] = None,
                      shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                      mesh: Optional[Mesh] = None,
                      ) -> Dict[str, PartitionSpec]:
    """Classify llama state-dict keys into the canonical family specs.

    Unknown families (norm weights, scalars) stay replicated under a
    pure-tp layout — correct for anything whose math runs identically
    on every chip; under an fsdp layout they take ``fsdp_default()``
    (dim0 storage shard) instead.

    ``shapes`` + ``mesh`` (required whenever ``layout.fsdp_axis`` is
    set) prune every spec against the actual dims: an axis that does
    not divide a dim is dropped from that dim's entry
    (:func:`prune_spec_axes`) — fsdp is a storage optimization that
    degrades instead of erroring, and BOTH the train step and the
    serving context run the same pruning so the placements agree
    (the zero-re-sharding contract).

    Serving-PTQ trees (``quantization.functional.quantize_param_tree``)
    interleave per-channel scale vectors under ``<param>::scale`` keys;
    those classify by their BASE weight's family — sharded with the
    output axis for column-sharded weights (qkv / gate / up / lm_head),
    replicated for row-sharded ones (o_proj / down) whose output axis
    is the hidden dim.  int8 weights themselves keep their family's
    2-D spec (quantization changes the dtype, not the layout).
    """
    from ..quantization.functional import WEIGHT_SCALE_SUFFIX
    layout = layout or SpecLayout()
    specs: Dict[str, PartitionSpec] = {}
    for k in keys:
        if k.endswith(WEIGHT_SCALE_SUFFIX):
            base = k[:-len(WEIGHT_SCALE_SUFFIX)]
            if any(p in base for p in ("q_proj", "k_proj", "v_proj",
                                       "gate_proj", "up_proj",
                                       "lm_head")):
                specs[k] = layout.col_weight_scale()
            elif "o_proj" in base or "down_proj" in base:
                specs[k] = layout.row_weight_scale()
            else:
                specs[k] = layout.replicated()
        elif "embed_tokens" in k:
            specs[k] = layout.embeddings()
        elif any(p in k for p in ("q_proj", "k_proj", "v_proj")):
            specs[k] = layout.qkv_bias() if k.endswith("bias") \
                else layout.qkv_projection()
        elif "o_proj" in k:
            specs[k] = layout.attn_output()
        elif "gate_proj" in k or "up_proj" in k:
            specs[k] = layout.ffn_up()
        elif "down_proj" in k:
            specs[k] = layout.ffn_down()
        elif "lm_head" in k:
            specs[k] = layout.lm_head()
        else:
            specs[k] = layout.fsdp_default()
    if shapes is not None and mesh is not None:
        specs = {k: prune_spec_axes(s, shapes[k], mesh)
                 if k in shapes else s for k, s in specs.items()}
    return specs


_EXPERT_BANK_FAMILIES = ("w_gate", "w_up", "w_down")


def mixtral_param_specs(keys: Iterable[str],
                        layout: Optional[SpecLayout] = None,
                        shapes: Optional[Dict[str, Tuple[int, ...]]]
                        = None,
                        mesh: Optional[Mesh] = None,
                        ) -> Dict[str, PartitionSpec]:
    """The MoE name classifier (round 24): batched expert banks
    (``...block_sparse_moe.w_gate/w_up/w_down``, plus their PTQ
    ``::scale`` tables) take :meth:`SpecLayout.expert_bank` —
    ``P(ep, None, None)`` — the router (``...block_sparse_moe.gate.*``)
    replicates (its logits drive a top-k whose ties must agree on every
    chip), and every other key delegates to :func:`llama_param_specs`
    (Mixtral's attention/embedding/lm_head ARE the llama families).
    Pruning semantics match llama_param_specs exactly."""
    from ..quantization.functional import WEIGHT_SCALE_SUFFIX
    layout = layout or SpecLayout()
    keys = list(keys)
    specs: Dict[str, PartitionSpec] = {}
    rest = []
    for k in keys:
        base = k[:-len(WEIGHT_SCALE_SUFFIX)] \
            if k.endswith(WEIGHT_SCALE_SUFFIX) else k
        if any(base.endswith(f) for f in _EXPERT_BANK_FAMILIES):
            specs[k] = layout.expert_bank_scale() if base != k \
                else layout.expert_bank()
        elif "block_sparse_moe.gate." in base:
            specs[k] = layout.replicated()
        else:
            rest.append(k)
    specs.update(llama_param_specs(rest, layout))
    if shapes is not None and mesh is not None:
        specs = {k: prune_spec_axes(s, shapes[k], mesh)
                 if k in shapes else s for k, s in specs.items()}
    return specs


# ---------------------------------------------------------------------------
# spec algebra (shared by the 2D train step and the serving prologue)
# ---------------------------------------------------------------------------
def _entry_names(entry) -> Tuple[str, ...]:
    """A PartitionSpec entry's axis names: None -> (), 'x' -> ('x',),
    ('x', 'y') -> ('x', 'y')."""
    if entry is None:
        return ()
    if isinstance(entry, tuple):
        return tuple(entry)
    return (entry,)


def spec_axes(spec: PartitionSpec) -> Tuple[str, ...]:
    """Every mesh axis a spec names, in dim order."""
    out = []
    for entry in spec:
        out.extend(_entry_names(entry))
    return tuple(out)


def prune_spec_axes(spec: PartitionSpec, shape: Tuple[int, ...],
                    mesh: Mesh) -> PartitionSpec:
    """Drop axis names a dim cannot honor: any name whose (cumulative)
    degree does not divide the dim size, and any spec entry past the
    array's rank.  The survivors are exactly the shardings
    ``NamedSharding(mesh, spec)`` can place, so train and serve agree
    on the SAME pruned placement by construction."""
    entries = []
    for dim, entry in enumerate(spec):
        if dim >= len(shape):
            break
        keep, part = [], 1
        for name in _entry_names(entry):
            size = mesh.shape.get(name, 1) if hasattr(mesh.shape, "get") \
                else dict(mesh.shape).get(name, 1)
            if size > 1 and shape[dim] % (part * size) == 0:
                keep.append(name)
                part *= size
        entries.append(tuple(keep) if len(keep) > 1
                       else (keep[0] if keep else None))
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def gather_spec_axes(x, spec: PartitionSpec,
                     axes: Optional[Sequence[str]] = None):
    """Inside a shard_map body: all-gather ``x`` (tiled, in axis-major
    order) along every dim whose spec entry names one of ``axes``
    (None = every named axis), reconstructing the full value from the
    placed shard.  The inverse of the per-dim sharding the spec
    declares — ONE tiled all-gather per (dim, axis) pair.  A tuple
    entry splits its dim major-to-minor, so the gather runs minor
    first (reversed) to land every block at its global offset."""
    for dim, entry in enumerate(spec):
        for name in reversed(_entry_names(entry)):
            if axes is None or name in axes:
                x = jax.lax.all_gather(x, name, axis=dim, tiled=True)
    return x


def fsdp_gather(x, spec: PartitionSpec, fsdp_axis: str):
    """The serving prologue's param gather: undo only the fsdp STORAGE
    shard, leaving the tp compute shard in place."""
    return gather_spec_axes(x, spec, (fsdp_axis,))


def shard_arrays(arrays: Dict[str, jnp.ndarray], mesh: Mesh,
                 specs: Dict[str, PartitionSpec]) -> Dict[str, jnp.ndarray]:
    """device_put each array with its spec's NamedSharding (the one-time
    placement at sharded-step init; params never cross the link again)."""
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in arrays.items()}


def validate_tp_serving(cfg, degree: int, pool_kv_heads: Optional[int]
                        = None) -> None:
    """Every divisibility constraint tensor-parallel serving needs,
    checked at ENGINE CONSTRUCTION with one actionable message —
    instead of a shard_map shape failure deep inside tracing."""
    if degree <= 1:
        return
    problems = []
    for name, val in (("num_attention_heads", cfg.num_attention_heads),
                      ("num_key_value_heads", cfg.num_key_value_heads),
                      ("vocab_size", cfg.vocab_size),
                      ("intermediate_size", cfg.intermediate_size)):
        if val % degree:
            problems.append(f"{name}={val}")
    if pool_kv_heads is not None \
            and pool_kv_heads != cfg.num_key_value_heads:
        problems.append(
            f"KV page pool has {pool_kv_heads} kv heads but the model "
            f"config says {cfg.num_key_value_heads}")
    if problems:
        raise ValueError(
            "tensor-parallel serving with tp=%d requires every sharded "
            "dimension to divide by tp; violated: %s.  Pick a tp that "
            "divides the head/vocab/ffn dims (or pad the model)."
            % (degree, ", ".join(problems)))


def validate_cp_serving(cp_degree: int, block_size: int,
                        quantized_kv: bool = False,
                        dense_prefill: bool = False,
                        spec_decode: bool = False) -> None:
    """Every constraint context-parallel serving needs, checked at
    ENGINE CONSTRUCTION with one actionable message (round 22,
    mirroring :func:`validate_tp_serving`).  cp stripes the pool's
    SLOT dim, so the page ``block_size`` must divide by cp; int8 KV,
    legacy dense prefill and speculative decoding are rejected (their
    pool/scatter layouts assume one chip holds a page's full slot
    range)."""
    if cp_degree <= 1:
        return
    if block_size % cp_degree:
        raise ValueError(
            f"context-parallel serving with cp={cp_degree} requires the "
            f"KV page block_size to divide by cp (each chip owns "
            f"block_size/cp slots of every page); got "
            f"block_size={block_size}.  Pick a block_size that divides "
            f"by cp, or lower cp.")
    if quantized_kv:
        raise ValueError(
            f"context-parallel serving (cp={cp_degree}) does not "
            f"support the int8 KV pool: the [phys_pages, Hkv] absmax "
            f"tables are page-global and would diverge across slot "
            f"stripes.  Serve with kv_dtype=None (fp32 pool) under cp.")
    if dense_prefill:
        raise ValueError(
            f"context-parallel serving (cp={cp_degree}) requires the "
            f"chunked/ragged prefill path; the legacy dense prefill "
            f"writes whole pages per chip and cannot stripe.  Construct "
            f"the engine with prefill_chunk_size set (paged prefill).")
    if spec_decode:
        raise ValueError(
            f"context-parallel serving (cp={cp_degree}) does not "
            f"support speculative decoding yet: the draft/verify steps "
            f"bypass the striped scatter.  Disable spec-decode under "
            f"cp.")


def validate_ep_serving(num_experts: int, ep_degree: int,
                        mixed_step: bool = True,
                        dense_prefill: bool = False,
                        spec_decode: bool = False,
                        budgets: Sequence[int] = ()) -> None:
    """Every constraint expert-parallel serving needs, checked at
    ENGINE CONSTRUCTION with one actionable message (round 24,
    mirroring :func:`validate_cp_serving`).  ep shards the expert
    banks' E dim and stripes the fused dispatch over token budgets, so
    both E and every compiled budget must divide by ep; the dispatch
    lives only in the mixed ragged step, so dense prefill and
    speculative decoding are rejected."""
    if ep_degree <= 1:
        return
    if not num_experts:
        raise ValueError(
            f"expert-parallel serving with ep={ep_degree} needs an MoE "
            f"model (num_local_experts on the config): a dense model "
            f"has no expert banks for the ep axis to shard — drop the "
            f"ep mesh axis or serve the Mixtral-family model.")
    if num_experts % ep_degree:
        raise ValueError(
            f"expert-parallel serving with ep={ep_degree} requires the "
            f"expert count to divide by ep (each chip owns E/ep "
            f"experts); got num_local_experts={num_experts}.  Pick an "
            f"ep that divides E, or lower ep.")
    if not mixed_step or dense_prefill:
        raise ValueError(
            f"expert-parallel serving (ep={ep_degree}) requires the "
            f"mixed ragged step: the token->expert all_to_all dispatch "
            f"is fused into the ONE compiled mixed launch, and the "
            f"legacy dense prefill/decode bodies have no ep stripe.  "
            f"Construct the engine with mixed_step=True.")
    if spec_decode:
        raise ValueError(
            f"expert-parallel serving (ep={ep_degree}) does not "
            f"support speculative decoding yet: the draft/verify steps "
            f"bypass the fused MoE dispatch.  Disable spec-decode "
            f"under ep.")
    bad = [b for b in budgets if int(b) % ep_degree]
    if bad:
        raise ValueError(
            f"expert-parallel serving (ep={ep_degree}) stripes each "
            f"compiled token budget over the ep axis, so every budget "
            f"must divide by ep; violated: {bad}.  Adjust the mixed "
            f"budget set (token_budgets) or lower ep.")


class TPContext:
    """Resolved tensor-parallel serving context, shared by every
    serving step of one engine: the jax mesh, the axis name/degree, the
    spec layout, the per-param specs, and the ONE placed copy of the
    sharded parameters (placed lazily on first use; params are
    read-only in serving, so they never cross the host link again)."""

    def __init__(self, mesh: Mesh, axis: Optional[str], degree: int,
                 layout: SpecLayout, specs: Dict[str, PartitionSpec],
                 fsdp_axis: Optional[str] = None, fsdp_degree: int = 1,
                 cp_axis: Optional[str] = None, cp_degree: int = 1,
                 ep_axis: Optional[str] = None, ep_degree: int = 1):
        self.mesh = mesh
        self.axis = axis                  # tp axis (None: pure fsdp)
        self.degree = degree              # tp degree (compute shard)
        self.fsdp_axis = fsdp_axis if fsdp_degree > 1 else None
        self.fsdp_degree = fsdp_degree if fsdp_degree > 1 else 1
        self.cp_axis = cp_axis if cp_degree > 1 else None
        self.cp_degree = cp_degree if cp_degree > 1 else 1
        self.ep_axis = ep_axis if ep_degree > 1 else None
        self.ep_degree = ep_degree if ep_degree > 1 else 1
        self.layout = layout
        self.specs = specs
        self._placed: Optional[Dict[str, jnp.ndarray]] = None
        self._placed_src: Dict[str, jnp.ndarray] = {}
        self._fsdp_bytes: Optional[int] = None

    def _place_one(self, k, v):
        """device_put UNLESS the array already carries exactly this
        sharding — then keep the buffer itself.  This is the
        train-to-serve zero-re-sharding contract: the 2D TrainStep's
        outputs are placed with the SAME mesh/specs, so serving them
        is pointer identity, not a host (or even device) copy."""
        sh = NamedSharding(self.mesh, self.specs[k])
        if isinstance(v, jax.Array) and getattr(v, "sharding", None) == sh:
            return v
        return jax.device_put(v, sh)

    def place_params(self, arrays: Dict[str, jnp.ndarray]
                     ) -> Dict[str, jnp.ndarray]:
        """Sharded placement with staleness tracking: jax arrays are
        immutable, so a weight update (checkpoint load, requantize)
        rebinds the source array — detected per key by identity against
        a HELD reference (a bare id() could be fooled by address reuse
        after the old array is freed) and only the changed params are
        re-placed.  Steady-state serving pays an `is` comparison per
        param, never a transfer; an array that ALREADY carries its
        target sharding (the 2D train step's placed output) is adopted
        by identity, never copied."""
        if self._placed is None:
            self._placed = {k: self._place_one(k, v)
                            for k, v in arrays.items()}
            self._placed_src = dict(arrays)
            return self._placed
        for k, v in arrays.items():
            if self._placed_src.get(k) is not v:
                self._placed[k] = self._place_one(k, v)
                self._placed_src[k] = v
        return self._placed

    def fsdp_gather_bytes(self, arrays: Dict[str, jnp.ndarray]) -> int:
        """Per-chip bytes RECEIVED by the serving prologue's fsdp param
        all-gathers in one sharded dispatch (0 without an fsdp axis):
        for each fsdp-sharded param, the chip holds 1/(tp_part*fsdp)
        and receives the other (fsdp-1) fsdp shards of its tp slice.
        Static per engine — cached on first call (the accounting behind
        ``spmd_allgather_bytes_total{site=...}``)."""
        if self.fsdp_axis is None:
            return 0
        if self._fsdp_bytes is not None:
            return self._fsdp_bytes
        sizes = dict(self.mesh.shape)
        total = 0
        for k, v in arrays.items():
            spec = self.specs.get(k)
            if spec is None:
                continue
            names = spec_axes(spec)
            if self.fsdp_axis not in names:
                continue
            part = 1
            for n in names:
                part *= sizes.get(n, 1)
            fdeg = sizes.get(self.fsdp_axis, 1)
            nbytes = int(np.prod(v.shape)) * v.dtype.itemsize \
                if v.shape else v.dtype.itemsize
            total += nbytes // part * (fdeg - 1)
        self._fsdp_bytes = total
        return total

    def collective_bytes(self, cfg, n_tokens: int,
                         n_gather_rows: int,
                         quant_gather: bool = False) -> Dict[str, int]:
        """Per-chip collective payload of ONE sharded serving dispatch:
        (1 + 2L) psums of [n_tokens, hidden] (embedding + the two
        per-layer boundaries) and one all-gather of the
        [n_gather_rows, vocab/tp] logits shard — the static-per-shape
        accounting behind ``serving_tp_collective_bytes_total``.

        ``quant_gather=True`` accounts the EQuARX-style int8 logits
        all-gather (``tp_gather_logits_q8``): one byte per logit plus
        the 4-byte per-shard scale — the payload the quantized
        collective actually moves (reported under
        ``serving_quant_collective_bytes_total`` too).

        With a cp axis (round 22) the attention stripe merge adds one
        ``all_gather`` of the ``(o, m, l)`` fp32 partial rows per layer
        — per chip ``L · n_tokens · H_local · (D + 2) · 4`` payload
        bytes received from each of the other ``cp - 1`` members —
        reported under the separate ``"cp_merge"`` key (routed to
        ``serving_cp_collective_bytes_total{op="all_gather"}``)."""
        if self.degree <= 1:
            # pure-fsdp / pure-cp serving: the body runs single-chip
            # math (no tp activation collectives)
            out = {"psum": 0, "all_gather": 0}
        else:
            item = 2 if cfg.dtype == "bfloat16" else 4
            shard = n_gather_rows * (cfg.vocab_size // self.degree)
            out = {
                "psum": (2 * cfg.num_hidden_layers + 1) * n_tokens
                * cfg.hidden_size * item,
                "all_gather": shard + 4 if quant_gather else shard * item,
            }
        if self.cp_degree > 1:
            h_local = cfg.num_attention_heads // self.degree
            d = cfg.hidden_size // cfg.num_attention_heads
            out["cp_merge"] = (cfg.num_hidden_layers * n_tokens
                               * h_local * (d + 2) * 4
                               * (self.cp_degree - 1))
        if self.ep_degree > 1:
            # round 24 MoE dispatch (per MoE layer): two all_to_all
            # exchanges of the [E, Tl*k, D] send/return buffers — the
            # chip keeps its own 1/ep slice, so (ep-1)/ep of each
            # buffer crosses the link — plus one all_gather where the
            # chip receives the other (ep-1) token stripes [Tl, D]
            item = 2 if cfg.dtype == "bfloat16" else 4
            ep = self.ep_degree
            E = cfg.num_local_experts
            k = cfg.num_experts_per_tok
            L = cfg.num_hidden_layers
            tl = n_tokens // ep
            buf = E * (tl * k) * cfg.hidden_size * item
            out["ep_all_to_all"] = 2 * L * buf * (ep - 1) // ep
            out["ep_all_gather"] = (L * (ep - 1) * tl
                                    * cfg.hidden_size * item)
        return out

    def pool_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.layout.kv_pool())

    def kv_scale_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.layout.kv_scale())

    def named(self, spec_tree):
        """PartitionSpec tree -> NamedSharding tree on this mesh (jit
        in_shardings/out_shardings from shard_map in_specs/out_specs)."""
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), spec_tree,
            is_leaf=lambda s: isinstance(s, PartitionSpec))

    def __repr__(self):
        return (f"TPContext(axis={self.axis!r}, degree={self.degree}, "
                f"fsdp_axis={self.fsdp_axis!r}, "
                f"fsdp_degree={self.fsdp_degree}, "
                f"cp_axis={self.cp_axis!r}, cp_degree={self.cp_degree}, "
                f"ep_axis={self.ep_axis!r}, ep_degree={self.ep_degree}, "
                f"mesh={tuple(self.mesh.shape.items())})")


def tp_serving_context(model, mesh, sharding: Optional[ShardingConfig]
                       = None) -> Optional[TPContext]:
    """Resolve engine-construction arguments into a :class:`TPContext`
    (or None when every sharding axis degenerates to 1 — run the
    single-chip step).  Validates every tp divisibility constraint up
    front; an ``fsdp`` mesh axis (round 21) composes weight-storage
    sharding on top (specs pruned per param shape), and any OTHER mesh
    axis — e.g. a ``dp`` replica axis — is simply never named by a
    spec, so weights and pools replicate across it."""
    cfg = sharding or ShardingConfig(axis="tp")
    from ..distributed.process_mesh import as_jax_mesh
    jmesh = as_jax_mesh(mesh) if mesh is not None else None
    fsdp_axis = "fsdp" if jmesh is not None \
        and "fsdp" in jmesh.axis_names else None
    fsdp_deg = jmesh.shape["fsdp"] if fsdp_axis else 1
    cp_axis = "cp" if jmesh is not None \
        and "cp" in jmesh.axis_names else None
    cp_deg = jmesh.shape["cp"] if cp_axis else 1
    ep_axis = "ep" if jmesh is not None \
        and "ep" in jmesh.axis_names else None
    ep_deg = jmesh.shape["ep"] if ep_axis else 1
    try:
        jmesh, axis, deg = resolve_mesh_axis(
            mesh, cfg.axis, cfg.degree, candidates=("tp", "model", "mp"))
    except ValueError:
        # no tp axis at all — a pure-fsdp (or fsdp×dp) mesh is still a
        # sharded-storage serving context, a pure-cp mesh (round 22) a
        # pool-striped one, and a pure-ep mesh (round 24) an
        # expert-sharded one (size-1 axes degenerate below); anything
        # else re-raises
        if fsdp_axis is None and cp_axis is None and ep_axis is None:
            raise
        axis, deg = None, 1
    if deg <= 1 and fsdp_deg <= 1 and cp_deg <= 1 and ep_deg <= 1:
        return None
    if deg > 1:
        validate_tp_serving(model.config, deg)
    layout = SpecLayout(tp_axis=axis if deg > 1 else None,
                        fsdp_axis=fsdp_axis if fsdp_deg > 1 else None,
                        cp_axis=cp_axis if cp_deg > 1 else None,
                        ep_axis=ep_axis if ep_deg > 1 else None)
    sd = model.state_dict()
    shapes = {k: tuple(t._value.shape) for k, t in sd.items()}
    specs_fn = mixtral_param_specs \
        if getattr(model.config, "num_local_experts", 0) \
        else llama_param_specs
    specs = specs_fn(sd.keys(), layout, shapes=shapes, mesh=jmesh)
    return TPContext(jmesh, axis if deg > 1 else None, deg, layout,
                     specs, fsdp_axis=fsdp_axis, fsdp_degree=fsdp_deg,
                     cp_axis=cp_axis if cp_deg > 1 else None,
                     cp_degree=cp_deg,
                     ep_axis=ep_axis if ep_deg > 1 else None,
                     ep_degree=ep_deg)


# ---------------------------------------------------------------------------
# traced helpers (composed inside the shard_map'd serving bodies)
# ---------------------------------------------------------------------------
def tp_embed(table_local, tokens, axis: str):
    """Vocab-parallel embedding lookup (Megatron): ``table_local`` is
    this chip's [V/tp, h] row shard; returns the REPLICATED [..., h]
    embeddings.  Exact: each token's row lives on exactly one chip, so
    the psum adds zeros from every other chip — bit-identical to the
    single-chip gather."""
    vs = table_local.shape[0]
    start = jax.lax.axis_index(axis).astype(jnp.int32) * vs
    local = tokens.astype(jnp.int32) - start
    ok = (local >= 0) & (local < vs)
    e = table_local[jnp.clip(local, 0, vs - 1)]
    e = jnp.where(ok[..., None], e, jnp.zeros((), e.dtype))
    return jax.lax.psum(e, axis)


def tp_gather_logits(logits_local, axis: str):
    """All-gather the [*, V/tp] vocab-sharded logits into the
    replicated [*, V] block (exact — pure concatenation in chip order,
    which IS vocab order under the column shard), so the on-device
    argmax sees the same values as the single-chip step."""
    return jax.lax.all_gather(logits_local, axis,
                              axis=logits_local.ndim - 1, tiled=True)


def tp_gather_logits_q8(logits_local, axis: str):
    """EQuARX-style (arXiv:2506.17615) quantized logits all-gather:
    each chip quantizes its [*, V/tp] vocab shard to symmetric int8
    with ONE per-shard absmax scale, the gather moves int8 codes (+ a
    4-byte scale each) instead of fp words — ~4× (fp32) / ~2× (bf16)
    less interconnect payload — and every chip dequantizes each shard
    with its own gathered scale before the argmax.

    NOT exact: two logits within ``absmax/127`` of each other can swap
    order after the round trip, so engines enable this behind a
    measured token-match-rate gate (a tolerance gate, not byte parity
    — the serving quantization bench reports the rate per workload).
    """
    from ..quantization.functional import (dequantize_symmetric,
                                           quantize_symmetric)
    x = logits_local.astype(jnp.float32)
    s = jnp.max(jnp.abs(x))                              # per-shard
    q = quantize_symmetric(x, s).astype(jnp.int8)
    gq = jax.lax.all_gather(q, axis, axis=q.ndim - 1, tiled=True)
    gs = jax.lax.all_gather(s, axis)                     # [tp]
    tp = gs.shape[0]
    lead, V = gq.shape[:-1], gq.shape[-1]
    out = dequantize_symmetric(gq.reshape(lead + (tp, V // tp)),
                               gs[:, None])
    return out.reshape(lead + (V,)).astype(logits_local.dtype)
