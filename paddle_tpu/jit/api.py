"""to_static implementation.

See package docstring.  Key pieces:

- ``StaticFunction``: wraps a python callable (or Layer method/Layer).  On
  call it (1) gathers the state of every Layer reachable from the callable
  (bound instance + closure scan), (2) traces a functionalized version under
  ``jax.jit`` keyed on the input signature — the analog of the reference's
  ProgramCache keyed on input spec (dy2static/program_translator.py), and
  (3) dispatches through the eager tape via apply_op so ``backward()`` runs
  the XLA-compiled VJP.
- RNG: a fresh fold-in key is passed as a real input each call, so dropout
  differs per step without retracing (reference analog: seed/offset fed to
  curand per launch).
- Guards/graph breaks (the SOT path, reference eval_frame.c) are not needed
  for full-graph mode; data-dependent Python control flow raises a tracing
  error like the reference's AST mode does for unsupported constructs.
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op
from ..nn.layer_base import Layer
from ..ops import random as _random

_TO_STATIC_ENABLED = [True]


def enable_to_static(flag: bool):
    """Parity: paddle.jit.enable_to_static."""
    _TO_STATIC_ENABLED[0] = bool(flag)


def not_to_static(fn=None):
    """Parity: paddle.jit.not_to_static — marker, fn runs eagerly."""
    if fn is None:
        return not_to_static
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    """Parity shim: paddle.jit.ignore_module."""
    return None


class InputSpec:
    """Parity: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


def _find_layers(fn) -> List[Layer]:
    """Find Layer objects the callable references — bound self, closure
    cells, defaults, and module globals the code names (into containers one
    level deep).  The analog of the reference's parameter collection in
    partial_program."""
    layers = []
    seen = set()

    def add(obj, depth=0):
        if isinstance(obj, Layer) and id(obj) not in seen:
            seen.add(id(obj))
            layers.append(obj)
        elif depth < 2 and isinstance(obj, (list, tuple)):
            for v in obj:
                add(v, depth + 1)
        elif depth < 2 and isinstance(obj, dict):
            for v in obj.values():
                add(v, depth + 1)

    if isinstance(fn, Layer):
        add(fn)
        return layers
    add(getattr(fn, "__self__", None))
    closure = getattr(fn, "__closure__", None)
    if closure:
        for cell in closure:
            try:
                add(cell.cell_contents)
            except ValueError:
                pass
    for v in (getattr(fn, "__defaults__", None) or ()):
        add(v)
    # module-scope layers referenced by name in the code object
    code = getattr(fn, "__code__", None)
    glb = getattr(fn, "__globals__", None)
    if code is not None and glb is not None:
        for name in code.co_names:
            if name in glb:
                add(glb[name])
    return layers


def _leaf_sig(a):
    """Signature of one flattened leaf.  Tensors key on shape/dtype;
    python scalars key on value (they are baked into the trace); anything
    else keys on repr so a changed value cannot hit a stale trace."""
    if isinstance(a, Tensor):
        return ("T", tuple(a._value.shape), str(a._value.dtype))
    if isinstance(a, (int, float, str, bool, type(None))):
        return ("P", a)
    return ("P", repr(a))


class StaticFunction:
    """Compiled callable (parity: dy2static StaticFunction /
    program_translator.py:776)."""

    def __init__(self, function, input_spec=None, build_strategy=None,
                 full_graph=True):
        self._fn = function
        self._input_spec = input_spec
        self._cache: Dict[Any, Callable] = {}
        self._layers: Optional[List[Layer]] = None
        self.__name__ = getattr(function, "__name__", "static_fn")
        functools.update_wrapper(self, function,
                                 assigned=("__doc__", "__module__"),
                                 updated=())

    # -- introspection parity ------------------------------------------------
    @property
    def code(self):
        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<source unavailable>"

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    def rollback(self):
        return self._fn

    # -- call ----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED[0] or getattr(self._fn, "_not_to_static",
                                                False):
            call = self._fn if not isinstance(self._fn, Layer) \
                else self._fn.forward
            return call(*args, **kwargs)

        if self._layers is None:
            self._layers = _find_layers(self._fn)

        # gather state (params + buffers) of involved layers
        state_items: List[Tuple[Layer, str, Tensor]] = []
        for li, layer in enumerate(self._layers):
            for k, t in layer.state_dict().items():
                state_items.append((layer, k, t))

        state_tensors = [t for _, _, t in state_items]
        flat_args, arg_tree = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        # numpy-array leaves behave as tensor inputs (not baked constants)
        flat_args = [Tensor(a) if isinstance(a, np.ndarray) else a
                     for a in flat_args]
        tensor_mask = [isinstance(a, Tensor) for a in flat_args]
        tensor_args = [a for a in flat_args if isinstance(a, Tensor)]
        static_args = [None if m else a
                       for a, m in zip(flat_args, tensor_mask)]

        # train/eval flags of every (sub)layer are part of the program key
        modes = tuple(l.training for layer in self._layers
                      for _, l in layer.named_sublayers(include_self=True))
        # the ambient bounded_loops bound changes how tensor whiles lower
        # (masked scan vs while_loop) — it must be part of the cache key
        from .convert_ops import _LOOP_BOUND
        loop_bound = getattr(_LOOP_BOUND, "n", None)
        sig = (str(arg_tree), tuple(_leaf_sig(a) for a in flat_args),
               tuple((tuple(t._value.shape), str(t._value.dtype))
                     for t in state_tensors), modes, loop_bound)

        compiled = self._cache.get(sig)
        if compiled is None:
            compiled = self._build(arg_tree, tensor_mask, static_args,
                                   state_items)
            self._cache[sig] = compiled

        key = _random.next_key()
        jit_fn, box = compiled
        outs = apply_op(f"static_fn::{self.__name__}", jit_fn,
                        (key, *state_tensors, *tensor_args))
        if not isinstance(outs, tuple):
            outs = (outs,)
        n_updates = len(box.get("updated_buffers", ()))
        if n_updates:
            # write mutated buffer values (BN running stats) back to their
            # host tensors — the compiled-region analog of the reference's
            # in-place running-stat outputs
            buf_tensors = box["updated_buffers"]
            for t, new in zip(buf_tensors, outs[len(outs) - n_updates:]):
                t._value = new._value
            outs = outs[: len(outs) - n_updates]
        return jax.tree_util.tree_unflatten(box["tree"], list(outs))

    def _build(self, arg_tree, tensor_mask, static_args, state_items):
        fn = self._fn
        layers = self._layers
        n_state = len(state_items)
        call = fn.forward if isinstance(fn, Layer) else fn
        # AST control-flow conversion (dy2static): tensor-predicate
        # if/while/for compile to lax.cond/while_loop instead of breaking
        # the trace (reference program_translator.py:776 AST mode).
        from .transformers import convert_to_static as _cvt
        call = _cvt(call)
        box: Dict[str, Any] = {}

        def traced(key, *vals):
            state_vals = vals[:n_state]
            arg_vals = list(vals[n_state:])
            # rebuild args structure
            flat = []
            it = iter(arg_vals)
            for m, s in zip(tensor_mask, static_args):
                flat.append(Tensor._from_value(next(it)) if m else s)
            args, kwargs = jax.tree_util.tree_unflatten(arg_tree, flat)

            # bind traced state into the layers
            import contextlib
            from ..nn.layer_base import Parameter
            with contextlib.ExitStack() as stack:
                offset = 0
                bound = []
                for layer in layers:
                    sd = layer.state_dict()
                    n = len(sd)
                    sub = {k: v for (_, k, _), v in zip(
                        state_items[offset:offset + n],
                        state_vals[offset:offset + n])}
                    stack.enter_context(layer.bind_state(sub))
                    bound.append((layer, sd, sub))
                    offset += n
                stack.enter_context(_random.trace_rng_scope(key))
                out = call(*args, **kwargs)

                # collect buffer mutations made during the traced call
                # (e.g. batch-norm running stats) before bind_state restores
                upd_tensors, upd_vals = [], []
                for layer, sd, sub in bound:
                    for k, t in sd.items():
                        if isinstance(t, Parameter):
                            continue
                        if k in sub and t._value is not sub[k]:
                            upd_tensors.append(t)
                            upd_vals.append(t._value)
                box["updated_buffers"] = upd_tensors

            flat, tree = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            box["tree"] = tree
            outs = tuple(t._value if isinstance(t, Tensor)
                         else jnp.asarray(t) for t in flat)
            return outs + tuple(upd_vals)

        return jax.jit(traced), box


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """Parity: @paddle.jit.to_static (python/paddle/jit/api.py:171).

    full_graph=True → AST-mode StaticFunction (whole-function jax.jit
    trace with control-flow conversion, reference dy2static).
    full_graph=False → SOT bytecode tracer (reference jit/sot): records
    the frame op-by-op, compiles on graph-break-free frames, falls back
    to eager otherwise."""
    def decorate(fn):
        if not full_graph:
            from .sot import SOTFunction
            return SOTFunction(fn, input_spec, build_strategy)
        return StaticFunction(fn, input_spec, build_strategy, full_graph)

    if function is None:
        return decorate
    return decorate(function)
