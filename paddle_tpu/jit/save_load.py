"""jit.save / jit.load.

Parity: python/paddle/jit/api.py save/load + TranslatedLayer
(python/paddle/jit/translated_layer.py) in the reference — a saved model is
the serialized compiled program + parameters, loadable without the original
Python class.

TPU-native: the "program" is a serialized StableHLO executable
(jax.export) — portable across processes and accelerators that XLA
supports; params are saved with paddle_tpu.save.  Inference-only (the
reference's jit.save also primarily targets deployment).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core.tensor import Tensor
from ..framework_io import save as _save, load as _load
from ..nn.layer_base import Layer
from .api import StaticFunction, InputSpec


def save(layer, path: str, input_spec=None, **configs):
    """Parity: paddle.jit.save.  Produces path.json (meta), path.pdexec
    (StableHLO), path.pdparams (state)."""
    if isinstance(layer, StaticFunction):
        static = layer
        base_layer = static._fn if isinstance(static._fn, Layer) else None
    elif isinstance(layer, Layer):
        base_layer = layer
        static = StaticFunction(layer)
    else:
        base_layer = None
        static = StaticFunction(layer)

    if input_spec is None:
        raise ValueError(
            "jit.save requires input_spec (list of InputSpec or example "
            "Tensors) to trace the program")

    from ..core import dtypes as _dt
    # all symbolic dims must share one scope -> create them in one call
    n_dyn = sum(
        sum(1 for s in spec.shape if s is None or (isinstance(s, int)
                                                   and s < 0))
        for spec in input_spec if isinstance(spec, InputSpec))
    sym_dims = list(jax_export.symbolic_shape(
        ", ".join(f"d{i}" for i in range(n_dyn)))) if n_dyn else []
    sym_it = iter(sym_dims)

    examples = []      # ShapeDtypeStruct (possibly symbolic) per input
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(jax.ShapeDtypeStruct(tuple(spec._value.shape),
                                                 spec._value.dtype))
        elif isinstance(spec, InputSpec):
            shape = tuple(next(sym_it) if (s is None or (isinstance(s, int)
                                                         and s < 0)) else s
                          for s in spec.shape)
            examples.append(jax.ShapeDtypeStruct(
                shape, _dt.convert_dtype(spec.dtype)))
        else:
            raise TypeError(f"bad input_spec entry {spec!r}")

    # collect state — keys prefixed per layer so two closure layers with
    # identical structured names cannot collide
    if static._layers is None:
        from .api import _find_layers
        static._layers = _find_layers(static._fn)
    state_items = []
    for li, layer_ in enumerate(static._layers):
        for k, t in layer_.state_dict().items():
            state_items.append((f"l{li}.{k}", t))
    # trace in eval mode, restoring the caller's train flags afterwards
    saved_modes = [(l, l.training)
                   for layer_ in static._layers
                   for _, l in layer_.named_sublayers(include_self=True)]
    for layer_ in static._layers:
        layer_.eval()

    call = static._fn.forward if isinstance(static._fn, Layer) else static._fn

    def infer_fn(state_vals, arg_vals):
        import contextlib
        from ..ops import random as _random
        with contextlib.ExitStack() as stack:
            offset = 0
            for layer_ in static._layers:
                sd = layer_.state_dict()
                n = len(sd)
                sub = {k: v for k, v in zip(
                    sd.keys(), state_vals[offset:offset + n])}
                stack.enter_context(layer_.bind_state(sub))
                offset += n
            # graftlint: waive[trace-prngkey] -- deterministic export: the fixed key IS the contract (a serialized module must not depend on ambient RNG)
            key0 = jax.random.PRNGKey(0)
            stack.enter_context(_random.trace_rng_scope(key0))
            out = call(*[Tensor._from_value(v) for v in arg_vals])
        flat, _ = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        return tuple(t._value if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in flat)

    state_vals = [t._value for _, t in state_items]
    try:
        exported = jax_export.export(jax.jit(infer_fn))(state_vals, examples)
    finally:
        for l, mode in saved_modes:
            l.training = mode
    blob = exported.serialize()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdexec", "wb") as f:
        f.write(blob)
    _save({k: t for k, t in state_items}, path + ".pdparams")
    meta = {
        "format": "paddle_tpu.jit.v1",
        "state_keys": [k for k, _ in state_items],
        "input_shapes": [[str(s) for s in t.shape] for t in examples],
        "input_dtypes": [str(t.dtype) for t in examples],
        "input_names": [
            (spec.name if isinstance(spec, InputSpec) and spec.name
             else f"x{i}") for i, spec in enumerate(input_spec)],
    }
    with open(path + ".json", "w") as f:
        json.dump(meta, f, indent=1)


class TranslatedLayer(Layer):
    """Loaded compiled model (parity: paddle.jit.TranslatedLayer)."""

    def __init__(self, exported, state: Dict[str, Tensor], meta: dict):
        super().__init__()
        self._exported = exported
        self._meta = meta
        self._state_keys = meta["state_keys"]
        self._state = state
        for k, t in state.items():
            self.register_buffer(k.replace(".", "__"), t)

    def forward(self, *inputs):
        state_vals = [self._state[k]._value for k in self._state_keys]
        arg_vals = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                    for t in inputs]
        outs = self._exported.call(state_vals, arg_vals)
        outs = tuple(Tensor._from_value(o) for o in outs)
        return outs[0] if len(outs) == 1 else outs


def load(path: str, **configs) -> TranslatedLayer:
    """Parity: paddle.jit.load."""
    with open(path + ".pdexec", "rb") as f:
        exported = jax_export.deserialize(f.read())
    state = _load(path + ".pdparams")
    with open(path + ".json") as f:
        meta = json.load(f)
    return TranslatedLayer(exported, state, meta)
