"""Gradient clipping.

Parity: python/paddle/nn/clip.py (reference — incl. the hybrid-parallel-aware
global-norm clip used by fleet).  The distributed engine extends
ClipGradByGlobalNorm to reduce the norm across mesh axes.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor._from_value(
                jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            v = g._value
            norm = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, Tensor._from_value((v * scale).astype(v.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Parity: paddle.nn.ClipGradByGlobalNorm.  In distributed runs the
    squared-norm partial sums are all-reduced over the relevant mesh axes by
    the hybrid optimizer wrapper before scaling."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _global_norm(self, grads):
        sq = [jnp.sum(jnp.square(g._value.astype(jnp.float32)))
              for g in grads]
        # grads may live on disjoint stage submeshes (pipeline parallel):
        # device-side addition across device sets is illegal, so when more
        # than one device group is present the partial sums are combined
        # on the host (the eager analog of the reference's hybrid clip
        # all-reducing partial norms across pp/mp groups).
        from ..core.device import device_group_key
        if len({device_group_key(g._value) for g in grads}) > 1:
            import numpy as _np
            return float(_np.sqrt(sum(float(_np.asarray(s)) for s in sq)))
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        return jnp.sqrt(total)

    def __call__(self, params_grads):
        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        gnorm = self._global_norm(grads)
        if isinstance(gnorm, float):   # cross-submesh host path
            scale = self.clip_norm / max(gnorm, self.clip_norm)
        else:
            scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor._from_value(
                (g._value * scale).astype(g._value.dtype))))
        return out
