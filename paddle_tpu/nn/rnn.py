"""Recurrent layers over lax.scan.

Parity: python/paddle/nn/layer/rnn.py (reference SimpleRNN/LSTM/GRU +
cuDNN-fused paths).  TPU-native: the time loop is a lax.scan so the whole
unrolled recurrence compiles into one XLA while-loop; no cuDNN analog
needed.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from .layer_base import Layer, Parameter
from . import initializer as I


class _RNNBase(Layer):
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        self.num_directions = ndir
        std = 1.0 / math.sqrt(hidden_size)
        g = self.GATES
        for layer in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer == 0 else hidden_size * ndir
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                self.add_parameter(
                    "weight_ih" + sfx,
                    self.create_parameter(
                        [g * hidden_size, in_sz],
                        default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    "weight_hh" + sfx,
                    self.create_parameter(
                        [g * hidden_size, hidden_size],
                        default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    "bias_ih" + sfx,
                    self.create_parameter(
                        [g * hidden_size],
                        default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    "bias_hh" + sfx,
                    self.create_parameter(
                        [g * hidden_size],
                        default_initializer=I.Uniform(-std, std)))

    # cell step: subclass implements (x_t, state, params) -> (state, out)
    def _cell(self, x_t, state, wih, whh, bih, bhh):
        raise NotImplementedError

    def _init_state(self, batch, dtype):
        raise NotImplementedError

    def _run_direction(self, x, layer, reverse, init_state):
        sfx = f"_l{layer}" + ("_reverse" if reverse else "")
        wih = getattr(self, "weight_ih" + sfx)
        whh = getattr(self, "weight_hh" + sfx)
        bih = getattr(self, "bias_ih" + sfx)
        bhh = getattr(self, "bias_hh" + sfx)

        def fn(xv, wihv, whhv, bihv, bhhv, *init):
            seq = xv if self.time_major else jnp.swapaxes(xv, 0, 1)
            if reverse:
                seq = jnp.flip(seq, 0)

            def step(carry, x_t):
                new = self._cell_val(x_t, carry, wihv, whhv, bihv, bhhv)
                out = new[0] if isinstance(new, tuple) else new
                return new, out

            carry0 = init if len(init) > 1 else init[0]
            carry, outs = jax.lax.scan(step, carry0, seq)
            if reverse:
                outs = jnp.flip(outs, 0)
            if not self.time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            if isinstance(carry, tuple):
                return (outs,) + tuple(carry)
            return outs, carry

        out = apply_op("rnn" + sfx, fn,
                       (x, wih, whh, bih, bhh, *init_state))
        return out

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if sequence_length is not None:
            raise NotImplementedError(
                "sequence_length masking is not supported yet; pad-free "
                "batches or mask outputs externally")
        x = inputs
        batch = x.shape[1] if self.time_major else x.shape[0]
        ndir = self.num_directions
        n_state = len(self._init_state(1, jnp.float32))

        states_out = []
        for layer in range(self.num_layers):
            dir_outs = []
            for d in range(ndir):
                if initial_states is None:
                    init = tuple(
                        Tensor(np.zeros((batch, self.hidden_size),
                                        np.float32))
                        for _ in range(n_state))
                else:
                    st = initial_states if n_state > 1 \
                        else (initial_states,)
                    idx = layer * ndir + d
                    init = tuple(s[idx] for s in st)
                res = self._run_direction(x, layer, d == 1, init)
                outs = res[0]
                states_out.append(tuple(res[1:]))
                dir_outs.append(outs)
            if ndir == 2:
                from ..ops.manipulation import concat
                x = concat(dir_outs, axis=-1)
            else:
                x = dir_outs[0]
            if self.dropout > 0 and layer < self.num_layers - 1:
                from . import functional as F
                x = F.dropout(x, self.dropout, training=self.training)

        from ..ops.manipulation import stack
        final = []
        for i in range(n_state):
            final.append(stack([s[i] for s in states_out], axis=0))
        if n_state == 1:
            return x, final[0]
        return x, tuple(final)


class SimpleRNN(_RNNBase):
    GATES = 1

    def __init__(self, *args, activation="tanh", **kwargs):
        self._act = jnp.tanh if activation == "tanh" else jax.nn.relu
        super().__init__(*args, **kwargs)

    def _init_state(self, batch, dtype):
        return (jnp.zeros((batch, self.hidden_size), dtype),)

    def _cell_val(self, x_t, h, wih, whh, bih, bhh):
        if isinstance(h, tuple):
            h = h[0]
        return self._act(x_t @ wih.T + bih + h @ whh.T + bhh)


class LSTM(_RNNBase):
    GATES = 4

    def _init_state(self, batch, dtype):
        return (jnp.zeros((batch, self.hidden_size), dtype),
                jnp.zeros((batch, self.hidden_size), dtype))

    def _cell_val(self, x_t, carry, wih, whh, bih, bhh):
        h, c = carry
        gates = x_t @ wih.T + bih + h @ whh.T + bhh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new)


class GRU(_RNNBase):
    GATES = 3

    def _init_state(self, batch, dtype):
        return (jnp.zeros((batch, self.hidden_size), dtype),)

    def _cell_val(self, x_t, carry, wih, whh, bih, bhh):
        h = carry[0] if isinstance(carry, tuple) else carry
        gi = x_t @ wih.T + bih
        gh = h @ whh.T + bhh
        ir, iz, inew = jnp.split(gi, 3, axis=-1)
        hr, hz, hnew = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(inew + r * hnew)
        return (1 - z) * n + z * h


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self._act = jnp.tanh if activation == "tanh" else jax.nn.relu
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size],
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [hidden_size], default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [hidden_size], default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ..ops import linalg as L
        if states is None:
            states = Tensor(np.zeros((inputs.shape[0], self.hidden_size),
                                     np.float32))
        pre = L.matmul(inputs, self.weight_ih, transpose_y=True) \
            + self.bias_ih \
            + L.matmul(states, self.weight_hh, transpose_y=True) \
            + self.bias_hh
        out = apply_op("rnn_cell_act", self._act, (pre,))
        return out, out
