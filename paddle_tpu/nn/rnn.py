"""Recurrent layers over lax.scan.

Parity: python/paddle/nn/layer/rnn.py (reference SimpleRNN/LSTM/GRU +
cuDNN-fused paths).  TPU-native: the time loop is a lax.scan so the whole
unrolled recurrence compiles into one XLA while-loop; no cuDNN analog
needed.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..ops._helpers import targ
from ..core.tensor import Tensor
from .layer_base import Layer, Parameter
from . import initializer as I


class _RNNBase(Layer):
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        self.num_directions = ndir
        std = 1.0 / math.sqrt(hidden_size)
        g = self.GATES
        for layer in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer == 0 else hidden_size * ndir
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                self.add_parameter(
                    "weight_ih" + sfx,
                    self.create_parameter(
                        [g * hidden_size, in_sz],
                        default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    "weight_hh" + sfx,
                    self.create_parameter(
                        [g * hidden_size, hidden_size],
                        default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    "bias_ih" + sfx,
                    self.create_parameter(
                        [g * hidden_size],
                        default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    "bias_hh" + sfx,
                    self.create_parameter(
                        [g * hidden_size],
                        default_initializer=I.Uniform(-std, std)))

    # cell step: subclass implements (x_t, state, params) -> (state, out)
    def _cell(self, x_t, state, wih, whh, bih, bhh):
        raise NotImplementedError

    def _init_state(self, batch, dtype):
        raise NotImplementedError

    def _run_direction(self, x, layer, reverse, init_state):
        sfx = f"_l{layer}" + ("_reverse" if reverse else "")
        wih = getattr(self, "weight_ih" + sfx)
        whh = getattr(self, "weight_hh" + sfx)
        bih = getattr(self, "bias_ih" + sfx)
        bhh = getattr(self, "bias_hh" + sfx)

        def fn(xv, wihv, whhv, bihv, bhhv, *init):
            seq = xv if self.time_major else jnp.swapaxes(xv, 0, 1)
            if reverse:
                seq = jnp.flip(seq, 0)

            def step(carry, x_t):
                new = self._cell_val(x_t, carry, wihv, whhv, bihv, bhhv)
                out = new[0] if isinstance(new, tuple) else new
                return new, out

            carry0 = init if len(init) > 1 else init[0]
            carry, outs = jax.lax.scan(step, carry0, seq)
            if reverse:
                outs = jnp.flip(outs, 0)
            if not self.time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            if isinstance(carry, tuple):
                return (outs,) + tuple(carry)
            return outs, carry

        out = apply_op("rnn" + sfx, fn,
                       (x, wih, whh, bih, bhh, *init_state))
        return out

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if sequence_length is not None:
            raise NotImplementedError(
                "sequence_length masking is not supported yet; pad-free "
                "batches or mask outputs externally")
        x = inputs
        batch = x.shape[1] if self.time_major else x.shape[0]
        ndir = self.num_directions
        n_state = len(self._init_state(1, jnp.float32))

        states_out = []
        for layer in range(self.num_layers):
            dir_outs = []
            for d in range(ndir):
                if initial_states is None:
                    init = tuple(
                        Tensor(np.zeros((batch, self.hidden_size),
                                        np.float32))
                        for _ in range(n_state))
                else:
                    st = initial_states if n_state > 1 \
                        else (initial_states,)
                    idx = layer * ndir + d
                    init = tuple(s[idx] for s in st)
                res = self._run_direction(x, layer, d == 1, init)
                outs = res[0]
                states_out.append(tuple(res[1:]))
                dir_outs.append(outs)
            if ndir == 2:
                from ..ops.manipulation import concat
                x = concat(dir_outs, axis=-1)
            else:
                x = dir_outs[0]
            if self.dropout > 0 and layer < self.num_layers - 1:
                from . import functional as F
                x = F.dropout(x, self.dropout, training=self.training)

        from ..ops.manipulation import stack
        final = []
        for i in range(n_state):
            final.append(stack([s[i] for s in states_out], axis=0))
        if n_state == 1:
            return x, final[0]
        return x, tuple(final)


class SimpleRNN(_RNNBase):
    GATES = 1

    def __init__(self, *args, activation="tanh", **kwargs):
        self._act = jnp.tanh if activation == "tanh" else jax.nn.relu
        super().__init__(*args, **kwargs)

    def _init_state(self, batch, dtype):
        return (jnp.zeros((batch, self.hidden_size), dtype),)

    def _cell_val(self, x_t, h, wih, whh, bih, bhh):
        if isinstance(h, tuple):
            h = h[0]
        return self._act(x_t @ wih.T + bih + h @ whh.T + bhh)


class LSTM(_RNNBase):
    GATES = 4

    def _init_state(self, batch, dtype):
        return (jnp.zeros((batch, self.hidden_size), dtype),
                jnp.zeros((batch, self.hidden_size), dtype))

    def _cell_val(self, x_t, carry, wih, whh, bih, bhh):
        h, c = carry
        gates = x_t @ wih.T + bih + h @ whh.T + bhh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new)


class GRU(_RNNBase):
    GATES = 3

    def _init_state(self, batch, dtype):
        return (jnp.zeros((batch, self.hidden_size), dtype),)

    def _cell_val(self, x_t, carry, wih, whh, bih, bhh):
        h = carry[0] if isinstance(carry, tuple) else carry
        gi = x_t @ wih.T + bih
        gh = h @ whh.T + bhh
        ir, iz, inew = jnp.split(gi, 3, axis=-1)
        hr, hz, hnew = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(inew + r * hnew)
        return (1 - z) * n + z * h


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self._act = jnp.tanh if activation == "tanh" else jax.nn.relu
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size],
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [hidden_size], default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [hidden_size], default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ..ops import linalg as L
        if states is None:
            states = Tensor(np.zeros((inputs.shape[0], self.hidden_size),
                                     np.float32))
        pre = L.matmul(inputs, self.weight_ih, transpose_y=True) \
            + self.bias_ih \
            + L.matmul(states, self.weight_hh, transpose_y=True) \
            + self.bias_hh
        out = apply_op("rnn_cell_act", self._act, (pre,))
        return out, out


class RNNCellBase(Layer):
    """Parity: paddle.nn.RNNCellBase — base for user cells consumed by
    the generic RNN/BiRNN wrappers."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        h = shape[-1] if shape is not None else self.hidden_size
        d = np.dtype(dtype) if dtype is not None else np.float32
        return Tensor(np.full((b, h), init_value, d))


class LSTMCell(RNNCellBase):
    """Parity: paddle.nn.LSTMCell (single-step LSTM)."""

    def __init__(self, input_size, hidden_size, **kw):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size],
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size],
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], default_initializer=I.Uniform(-std, std))

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        # LSTM state is an (h, c) pair
        h = super().get_initial_states(batch_ref, shape, dtype,
                                       init_value, batch_dim_idx)
        c = super().get_initial_states(batch_ref, shape, dtype,
                                       init_value, batch_dim_idx)
        return (h, c)

    def forward(self, inputs, states=None):
        if states is None:
            z = Tensor(np.zeros((inputs.shape[0], self.hidden_size),
                                np.float32))
            states = (z, z)
        h, c = states

        def fn(x, hv, cv, wih, whh, bih, bhh):
            gates = x @ wih.T + bih + hv @ whh.T + bhh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * cv + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = apply_op(
            "lstm_cell", fn,
            (inputs, targ(h), targ(c), targ(self.weight_ih),
             targ(self.weight_hh), targ(self.bias_ih),
             targ(self.bias_hh)))
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    """Parity: paddle.nn.GRUCell (single-step GRU)."""

    def __init__(self, input_size, hidden_size, **kw):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size],
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size],
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            states = Tensor(np.zeros((inputs.shape[0], self.hidden_size),
                                     np.float32))

        def fn(x, hv, wih, whh, bih, bhh):
            gi = x @ wih.T + bih
            gh = hv @ whh.T + bhh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            cand = jnp.tanh(ic + r * hc)
            return (1.0 - z) * cand + z * hv

        h_new = apply_op(
            "gru_cell", fn,
            (inputs, targ(states), targ(self.weight_ih),
             targ(self.weight_hh), targ(self.bias_ih),
             targ(self.bias_hh)))
        return h_new, h_new


class RNN(Layer):
    """Parity: paddle.nn.RNN — run any cell over the time axis.

    The step loop is a python loop over the (static) sequence length in
    eager mode; under jit the whole unrolled graph compiles once (cells
    are tiny — XLA fuses the per-step work)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if sequence_length is not None and self.is_reverse:
            # per-example reversal: run forward over sequences reversed
            # within their valid lengths, then un-reverse the outputs —
            # the reverse direction thus starts at each example's last
            # valid step, not at padding
            rev = _reverse_sequence(inputs, sequence_length,
                                    self.time_major)
            out, state = self._run(rev, initial_states, sequence_length,
                                   is_reverse=False)
            return _reverse_sequence(out, sequence_length,
                                     self.time_major), state
        return self._run(inputs, initial_states, sequence_length,
                         is_reverse=self.is_reverse)

    def _run(self, inputs, initial_states, sequence_length, is_reverse):
        from ..ops.manipulation import stack
        from ..ops import where as _where, zeros_like
        x = inputs
        time_axis = 0 if self.time_major else 1
        steps = x.shape[time_axis]
        order = range(steps - 1, -1, -1) if is_reverse \
            else range(steps)
        state = initial_states
        outs = [None] * steps

        def blend(new, old, active):
            # finished sequences freeze their state and emit zeros
            if old is None:
                return new
            if isinstance(new, (tuple, list)):
                return type(new)(blend(n, o, active)
                                 for n, o in zip(new, old))
            return _where(active, new, old)

        for t in order:
            x_t = x[t] if self.time_major else x[:, t]
            out, new_state = self.cell(x_t, state)
            if sequence_length is not None:
                active = (sequence_length > t).reshape([-1, 1])
                new_state = blend(new_state, state, active)
                out = _where(active, out, zeros_like(out))
            state = new_state
            outs[t] = out
        return stack(outs, axis=time_axis), state


def _reverse_sequence(x, lengths, time_major):
    """Reverse each example's first lengths[b] steps in place; padding
    steps keep their positions (paddle's sequence-reverse semantics)."""
    def fn(xv, lv):
        lv = lv.astype(jnp.int32)
        steps = xv.shape[0 if time_major else 1]
        t = jnp.arange(steps, dtype=jnp.int32)
        if time_major:
            idx = jnp.where(t[:, None] < lv[None, :],
                            lv[None, :] - 1 - t[:, None], t[:, None])
            idx = idx.reshape(steps, lv.shape[0],
                              *([1] * (xv.ndim - 2)))
            return jnp.take_along_axis(xv, idx, axis=0)
        idx = jnp.where(t[None, :] < lv[:, None],
                        lv[:, None] - 1 - t[None, :], t[None, :])
        idx = idx.reshape(lv.shape[0], steps, *([1] * (xv.ndim - 2)))
        return jnp.take_along_axis(xv, idx, axis=1)
    return apply_op("reverse_sequence", fn, (x, targ(lengths)))


class BiRNN(Layer):
    """Parity: paddle.nn.BiRNN — forward + backward cells, outputs
    concatenated on the feature axis.  With sequence_length, the
    backward direction runs over per-example-reversed inputs so it
    starts at each example's last valid step (not at padding)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import concat
        init_fw, init_bw = (initial_states
                            if initial_states is not None else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, init_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, init_bw, sequence_length)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
