"""paddle.nn.quant (parity: python/paddle/nn/quant/__init__.py —
Stub + the weight-only linear family; fake-quant layers in
quant_layers.py).  Kernels live in ops/op_surface.py (int8 pack +
dequant-into-matmul on the MXU)."""
from ...ops.op_surface import (weight_only_linear, llm_int8_linear,
                               weight_quantize, weight_dequantize)
from . import quant_layers  # noqa: F401
from ..layer_base import Layer

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize"]


class Stub(Layer):
    """Parity: nn/quant/stub.py Stub — a quantization insertion point:
    identity in float graphs, replaced by a QuanterStub (observer) when
    a QAT config quantizes the model."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x
