"""Fake-quantization layers (parity: python/paddle/nn/quant/
quant_layers.py — the QAT building blocks).

All quantizers are symmetric-absmax with straight-through-estimator
gradients, built on the shared ``_fake_quant`` op
(paddle_tpu/quantization/__init__.py) so they fuse into the surrounding
matmul under jit.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Tensor
from ..layer_base import Layer
from ... import nn as _nn

__all__ = ["FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
           "FakeQuantChannelWiseAbsMax", "QuantizedConv2D",
           "QuantizedConv2DTranspose", "QuantizedLinear",
           "MovingAverageAbsMaxScale", "MAOutputScaleLayer",
           "FakeQuantMAOutputScaleLayer", "QuantStub",
           "QuantizedRowParallelLinear",
           "QuantizedColumnParallelLinear", "QuantizedMatmul"]


def _fq(x, scale, bits):
    from ...quantization import _fake_quant
    return _fake_quant(x, scale, bit_length=bits)


class FakeQuantAbsMax(Layer):
    """Per-tensor absmax fake quant (parity: quant_layers.FakeQuantAbsMax)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32",
                 reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits
        self.scale = None

    def forward(self, x):
        scale = jnp.max(jnp.abs(x._value)).astype(jnp.float32)
        self.scale = Tensor._from_value(scale)
        return _fq(x, scale, self._quant_bits)


class FakeQuantMovingAverageAbsMax(Layer):
    """Moving-average absmax activation quant (parity:
    quant_layers.FakeQuantMovingAverageAbsMax)."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32", reduce_type=None):
        super().__init__()
        self._rate = moving_rate
        self._quant_bits = quant_bits
        self._scale = None

    def forward(self, x):
        cur = jnp.max(jnp.abs(x._value)).astype(jnp.float32)
        if self.training:
            self._scale = cur if self._scale is None else \
                self._rate * self._scale + (1 - self._rate) * cur
        scale = self._scale if self._scale is not None else cur
        return _fq(x, scale, self._quant_bits)

    @property
    def scale(self):
        return None if self._scale is None else \
            Tensor._from_value(self._scale)


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-channel weight quant (parity:
    quant_layers.FakeQuantChannelWiseAbsMax)."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32", reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits
        self._axis = quant_axis
        self.scale = None

    def forward(self, w):
        axes = tuple(i for i in range(w._value.ndim) if i != self._axis)
        scale = jnp.max(jnp.abs(w._value), axis=axes).astype(jnp.float32)
        self.scale = Tensor._from_value(scale)
        shape = [1] * w._value.ndim
        shape[self._axis] = -1
        return _fq(w, scale.reshape(shape), self._quant_bits)


class MovingAverageAbsMaxScale(Layer):
    """Track (not quantize) the moving-average output scale (parity:
    quant_layers.MovingAverageAbsMaxScale)."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32",
                 reduce_type=None):
        super().__init__()
        self._rate = moving_rate
        self._scale = None

    def forward(self, x):
        if self.training:
            cur = jnp.max(jnp.abs(x._value)).astype(jnp.float32)
            self._scale = cur if self._scale is None else \
                self._rate * self._scale + (1 - self._rate) * cur
        return x

    @property
    def scale(self):
        return None if self._scale is None else \
            Tensor._from_value(self._scale)


class QuantStub(Layer):
    """Input quant stub (parity: quant_layers QuantStub)."""

    def __init__(self, moving_rate=0.9, quant_bits=8):
        super().__init__()
        self._q = FakeQuantMovingAverageAbsMax(moving_rate=moving_rate,
                                               quant_bits=quant_bits)

    def forward(self, x):
        return self._q(x)


class _QuantizedWrap(Layer):
    """Shared fake-quant wrapper: quantize activations (moving-average
    absmax) and weights (channel-wise absmax) then run the float op."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quant_axis=0):
        super().__init__()
        self._inner = layer
        self._act_q = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)
        self._w_q = FakeQuantChannelWiseAbsMax(
            quant_bits=weight_bits, quant_axis=weight_quant_axis)

    def forward(self, x):
        xq = self._act_q(x)
        w = self._inner.weight
        wq = self._w_q(w)
        return self._apply(xq, wq)


class QuantizedLinear(_QuantizedWrap):
    """Parity: quant_layers.QuantizedLinear."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kw):
        super().__init__(layer, weight_bits, activation_bits,
                         moving_rate, weight_quant_axis=1)

    def _apply(self, xq, wq):
        from ...ops.linalg import matmul
        out = matmul(xq, wq)
        if self._inner.bias is not None:
            out = out + self._inner.bias
        return out


class QuantizedConv2D(_QuantizedWrap):
    """Parity: quant_layers.QuantizedConv2D."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kw):
        super().__init__(layer, weight_bits, activation_bits,
                         moving_rate, weight_quant_axis=0)

    def _apply(self, xq, wq):
        from ..functional import conv2d
        c = self._inner
        return conv2d(xq, wq, bias=c.bias, stride=c._stride,
                      padding=c._padding, dilation=c._dilation,
                      groups=c._groups, data_format=c._data_format)


class QuantizedConv2DTranspose(_QuantizedWrap):
    """Parity: quant_layers.QuantizedConv2DTranspose."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kw):
        super().__init__(layer, weight_bits, activation_bits,
                         moving_rate, weight_quant_axis=1)

    def _apply(self, xq, wq):
        from ..functional import conv2d_transpose
        c = self._inner
        return conv2d_transpose(
            xq, wq, bias=c.bias, stride=c._stride, padding=c._padding,
            dilation=c._dilation, groups=c._groups,
            output_padding=getattr(c, "_output_padding", 0),
            data_format=c._data_format)


class QuantizedMatmul(Layer):
    """Parity: quant_layers.QuantizedMatmul — fake-quant both operands
    of a matmul."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kw):
        super().__init__()
        self._qx = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)
        self._qy = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, x, y, transpose_x=False, transpose_y=False,
                name=None):
        from ...ops.linalg import matmul
        return matmul(self._qx(x), self._qy(y), transpose_x=transpose_x,
                      transpose_y=transpose_y)


class MAOutputScaleLayer(Layer):
    """Wrap a layer, tracking its output scale (parity:
    quant_layers.MAOutputScaleLayer)."""

    def __init__(self, layer, moving_rate=0.9, name=None,
                 dtype="float32", reduce_type=None):
        super().__init__()
        self._layer = layer
        self._ma = MovingAverageAbsMaxScale(moving_rate=moving_rate)

    def forward(self, *args, **kwargs):
        out = self._layer(*args, **kwargs)
        return self._ma(out)


class FakeQuantMAOutputScaleLayer(Layer):
    """Wrap a layer, fake-quantizing its output with a moving-average
    scale (parity: quant_layers.FakeQuantMAOutputScaleLayer)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, name=None, reduce_type=None):
        super().__init__()
        self._layer = layer
        self._q = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, *args, **kwargs):
        return self._q(self._layer(*args, **kwargs))


class _QuantizedParallelLinear(Layer):
    """Shared body for the tensor-parallel quantized linears: fake-quant
    input + weight, delegate to the wrapped mp layer's collective
    forward."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quant_axis=1):
        super().__init__()
        self._inner = layer
        self._act_q = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)
        self._w_q = FakeQuantChannelWiseAbsMax(
            quant_bits=weight_bits, quant_axis=weight_quant_axis)

    def forward(self, x):
        xq = self._act_q(x)
        w = self._inner.weight
        saved = w._value
        wq = self._w_q(w)
        try:
            w._value = wq._value
            return self._inner(xq)
        finally:
            w._value = saved


class QuantizedColumnParallelLinear(_QuantizedParallelLinear):
    """Parity: quant_layers.QuantizedColumnParallelLinear."""


class QuantizedRowParallelLinear(_QuantizedParallelLinear):
    """Parity: quant_layers.QuantizedRowParallelLinear."""
