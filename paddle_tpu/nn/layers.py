"""Standard layers.

Parity: python/paddle/nn/layer/{common,conv,norm,pooling,loss,activation}.py
(reference).  Layers are thin parameter containers over the functional ops.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .layer_base import Layer, Parameter
from . import functional as F
from . import initializer as I


class Linear(Layer):
    """y = xW + b, W:[in, out] (parity: paddle.nn.Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        w = self.weight
        return f"in={w.shape[0]}, out={w.shape[1]}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            val = np.array(self.weight.numpy())
            val[padding_idx] = 0
            self.weight.set_value(val)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, self.p, self.axis, self.training, self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, self.training, self.data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ..ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *a, **k):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._args = (size, scale_factor, mode, align_corners, align_mode,
                      data_format)

    def forward(self, x):
        return F.interpolate(x, *self._args)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor, self.data_format)


# -- containers --------------------------------------------------------------
class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


# -- conv --------------------------------------------------------------------
class _ConvNd(Layer):
    def __init__(self, nd, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        from .functional.conv import _pair
        k = _pair(kernel_size, nd)
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups = groups
        self._data_format = data_format
        self._transpose = transpose
        self._output_padding = output_padding
        if transpose:
            wshape = [in_channels, out_channels // groups] + list(k)
        else:
            wshape = [out_channels, in_channels // groups] + list(k)
        fan_in = in_channels * int(np.prod(k)) // groups
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr,
                default_initializer=I.Uniform(-bound, bound), is_bias=True)
        else:
            self.bias = None


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)


# -- norm --------------------------------------------------------------------
class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(np.zeros(num_features,
                                                      np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features,
                                                         np.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, self.training, self._momentum,
                            self._epsilon, self._data_format,
                            self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCW" if data_format in ("NCL", "NCW")
                         else "NWC", use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync falls out of SPMD compilation: under pjit the
    batch axis is sharded and XLA inserts the cross-replica reductions for
    the mean/var (parity intent of paddle.nn.SyncBatchNorm without a
    dedicated comm kernel)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class RMSNorm(Layer):
    """Parity: fused_rms_norm surface (reference #17) as a layer."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self._args)


# -- pooling -----------------------------------------------------------------
class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCHW",
                 name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, return_mask, ceil_mode,
                      data_format)

    def forward(self, x):
        return F.max_pool2d(x, *self._args)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, exclusive,
                      divisor_override, data_format)

    def forward(self, x):
        return F.avg_pool2d(x, *self._args)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, return_mask, ceil_mode)

    def forward(self, x):
        return F.max_pool1d(x, *self._args)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, exclusive, ceil_mode)

    def forward(self, x):
        return F.avg_pool1d(x, *self._args)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


# -- activations as layers ---------------------------------------------------
def _act_layer(name, fn, **default_kw):
    class _Act(Layer):
        def __init__(self, *a, **kw):
            super().__init__()
            self._a = a
            self._kw = {**default_kw, **kw}
            self._kw.pop("name", None)

        def forward(self, x):
            return fn(x, *self._a, **self._kw)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
GELU = _act_layer("GELU", F.gelu)
SiLU = _act_layer("SiLU", F.silu)
Swish = _act_layer("Swish", F.swish)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Tanh = _act_layer("Tanh", F.tanh)
Softmax = _act_layer("Softmax", F.softmax)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax)
Softplus = _act_layer("Softplus", F.softplus)
Softsign = _act_layer("Softsign", F.softsign)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu)
ELU = _act_layer("ELU", F.elu)
CELU = _act_layer("CELU", F.celu)
SELU = _act_layer("SELU", F.selu)
Mish = _act_layer("Mish", F.mish)
Hardtanh = _act_layer("Hardtanh", F.hardtanh)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardswish = _act_layer("Hardswish", F.hardswish)
Hardshrink = _act_layer("Hardshrink", F.hardshrink)
Softshrink = _act_layer("Softshrink", F.softshrink)
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
LogSigmoid = _act_layer("LogSigmoid", F.log_sigmoid)
ThresholdedReLU = _act_layer("ThresholdedReLU", F.thresholded_relu)
Maxout = _act_layer("Maxout", F.maxout)
GLU = _act_layer("GLU", F.glu)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


# -- losses as layers --------------------------------------------------------
def _loss_layer(name, fn):
    class _Loss(Layer):
        def __init__(self, *a, **kw):
            super().__init__()
            self._a = a
            self._kw = kw
            self._kw.pop("name", None)

        def forward(self, input, label, *extra):
            return fn(input, label, *extra, *self._a, **self._kw)

    _Loss.__name__ = name
    _Loss.__qualname__ = name
    return _Loss


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self._kw = dict(weight=weight, ignore_index=ignore_index,
                        reduction=reduction, soft_label=soft_label,
                        axis=axis, use_softmax=use_softmax,
                        label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, **self._kw)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self._kw = dict(weight=weight, ignore_index=ignore_index,
                        reduction=reduction)

    def forward(self, input, label):
        return F.nll_loss(input, label, **self._kw)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight, self._reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self._weight,
                                      self._reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self._kw = dict(weight=weight, reduction=reduction,
                        pos_weight=pos_weight)

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, **self._kw)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self._reduction, self._log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self._reduction, self._log_target)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._reduction, self._delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self._reduction, self._delta)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self._blank, self._reduction, norm_by_times)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin,
                                     self._reduction)


# -- padding layers ----------------------------------------------------------
class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (padding, mode, value, data_format)

    def forward(self, x):
        return F.pad(x, *self._args)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, self.training)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, *self._args)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool2d(x, *self._args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self._args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        return F.fold(x, *self._args)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0,
                         data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0,
                         data_format)


class Bilinear(Layer):
    """y_o = x1^T W_o x2 + b_o (parity: paddle.nn.Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self._axis, self._eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self._axis, self._eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._p, self._eps, self._keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ..ops.linalg import norm as _norm
        diff = x - y + self._eps
        return _norm(diff, p=self._p, axis=-1, keepdim=self._keepdim)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._kw = dict(p=p, margin=margin, weight=weight,
                        reduction=reduction)

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, **self._kw)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._kw = dict(weight=weight, reduction=reduction)

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, **self._kw)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._kw = dict(log_input=log_input, full=full, epsilon=epsilon,
                        reduction=reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, **self._kw)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self._kw = dict(full=full, epsilon=epsilon, reduction=reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, **self._kw)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._kw = dict(margin=margin, p=p, epsilon=epsilon, swap=swap,
                        reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, **self._kw)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, exclusive,
                      divisor_override, data_format)

    def forward(self, x):
        return F.avg_pool3d(x, *self._args)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCDHW",
                 name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, return_mask, ceil_mode,
                      data_format)

    def forward(self, x):
        return F.max_pool3d(x, *self._args)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._args = (output_size, data_format)

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, *self._args)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, return_mask)

    def forward(self, x):
        return F.adaptive_max_pool1d(x, *self._args)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, return_mask)

    def forward(self, x):
        return F.adaptive_max_pool3d(x, *self._args)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, *self._args)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, *self._args)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self._args = (padding, mode, value,
                      "NCW" if data_format == "NCL" else data_format)

    def forward(self, x):
        return F.pad(x, *self._args)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__()
        self._args = (padding, mode, value, data_format)

    def forward(self, x):
        return F.pad(x, *self._args)


class InstanceNorm1D(Layer):
    """Parity: paddle.nn.InstanceNorm1D ([N, C, L])."""

    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._eps = epsilon
        self.scale = None if weight_attr is False else \
            self.create_parameter([num_features], attr=weight_attr,
                                  default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else \
            self.create_parameter([num_features], attr=bias_attr,
                                  is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._eps, data_format="NCL")


class InstanceNorm3D(InstanceNorm1D):
    """Parity: paddle.nn.InstanceNorm3D ([N, C, D, H, W])."""

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._eps, data_format="NCDHW")


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self._margin,
                                       self._reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self._margin,
                                      self._reduction)


class TripletMarginWithDistanceLoss(Layer):
    """Parity: paddle.nn.TripletMarginWithDistanceLoss — triplet loss
    with a user distance callable (default: pairwise L2)."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._dist = distance_function
        self._margin, self._swap = margin, swap
        self._reduction = reduction

    def forward(self, input, positive, negative):
        if self._dist is None:
            return F.triplet_margin_loss(
                input, positive, negative, margin=self._margin,
                swap=self._swap, reduction=self._reduction)
        d_pos = self._dist(input, positive)
        d_neg = self._dist(input, negative)
        if self._swap:
            from ..ops import minimum
            d_neg = minimum(d_neg, self._dist(positive, negative))
        from ..ops import clip, mean as _mean, sum as _sum
        loss = clip(d_pos - d_neg + self._margin, min=0.0)
        if self._reduction == "mean":
            return _mean(loss)
        if self._reduction == "sum":
            return _sum(loss)
        return loss


class LayerDict(Layer):
    """Parity: paddle.nn.LayerDict — ordered dict of sublayers."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if hasattr(sublayers, "items") \
            else sublayers
        for key, layer in items:
            self.add_sublayer(key, layer)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self._axis, self._shape = axis, shape

    def forward(self, x):
        from ..ops.extras import unflatten
        return unflatten(x, self._axis, self._shape)


class Silu(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.silu(x)


class Softmax2D(Layer):
    """Parity: paddle.nn.Softmax2D — softmax over the channel dim of
    [N, C, H, W] (or [C, H, W])."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.softmax(x, axis=-3)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, self.training)


class Conv3DTranspose(_ConvNd):
    """Parity: paddle.nn.Conv3DTranspose (conv.py reference)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)


class SpectralNorm(Layer):
    """Parity: paddle.nn.SpectralNorm (python/paddle/nn/layer/norm.py) —
    a layer that spectrally normalizes a WEIGHT tensor passed to
    forward: W / sigma_max(W), sigma estimated by persistent power
    iteration over the matricized weight (dim rows)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        shape = list(weight_shape)
        h = shape[dim]
        w = 1
        for i, s in enumerate(shape):
            if i != dim:
                w *= s
        import numpy as _np
        rng = _np.random.RandomState(0)
        self.register_buffer("weight_u", Tensor(
            rng.randn(h).astype("float32")))
        self.register_buffer("weight_v", Tensor(
            rng.randn(w).astype("float32")))

    def forward(self, weight):
        from ..autograd.tape import no_grad
        mat = weight
        if self._dim != 0:
            perm = [self._dim] + [i for i in range(len(weight.shape))
                                  if i != self._dim]
            mat = weight.transpose(perm)
        h = mat.shape[0]
        mat2 = mat.reshape([h, -1])
        u, v = self.weight_u, self.weight_v
        with no_grad():
            for _ in range(self._power_iters):
                v = F.normalize(mat2.t().matmul(u.unsqueeze(1)).squeeze(1),
                                epsilon=self._eps, axis=0)
                u = F.normalize(mat2.matmul(v.unsqueeze(1)).squeeze(1),
                                epsilon=self._eps, axis=0)
            self.weight_u.set_value(u.numpy())
            self.weight_v.set_value(v.numpy())
        sigma = u.unsqueeze(0).matmul(mat2).matmul(
            v.unsqueeze(1)).reshape([])
        return weight / sigma


class FeatureAlphaDropout(Layer):
    """Parity: paddle.nn.FeatureAlphaDropout — alpha dropout that drops
    whole channels (feature maps), preserving self-normalizing
    statistics (SELU alpha')."""

    _ALPHA_P = 1.7580993408473766   # -selu_alpha * selu_scale

    def __init__(self, p=0.5, name=None):
        super().__init__()
        if not 0.0 <= p < 1.0:
            raise ValueError(
                f"FeatureAlphaDropout p must be in [0, 1), got {p}")
        self.p = p

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        from ..ops.random import next_key
        import jax as _jax

        p = self.p
        alpha_p = -self._ALPHA_P
        a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
        b = -a * alpha_p * p
        key = next_key()

        def fn(v):
            shape = (v.shape[0], v.shape[1]) + (1,) * (v.ndim - 2)
            keep = _jax.random.bernoulli(key, 1 - p, shape)
            return (jnp.where(keep, v, alpha_p) * a + b).astype(v.dtype)

        from ..core.dispatch import apply_op
        return apply_op("feature_alpha_dropout", fn, (x,))


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Parity: paddle.nn.AdaptiveLogSoftmaxWithLoss
    (python/paddle/nn/layer/loss.py) — hierarchical softmax with
    frequency cutoffs: a head over [common classes + cluster tokens] and
    per-cluster tail projections of decreasing width (div_value)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if (not cutoffs or cutoffs != sorted(cutoffs)
                or len(set(cutoffs)) != len(cutoffs)
                or cutoffs[-1] > n_classes - 1 or min(cutoffs) <= 0):
            raise ValueError(
                "cutoffs must be unique, increasing, positive ints "
                "< n_classes")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.shortlist_size = self.cutoffs[0]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.shortlist_size + self.n_clusters
        self.head = Linear(in_features, self.head_size,
                           bias_attr=head_bias if head_bias else False)
        self.tail = LayerList()
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            self.tail.append(Sequential(
                Linear(in_features, hsz, bias_attr=False),
                Linear(hsz, osz, bias_attr=False)))

    def _full_log_prob(self, input):
        head_out = F.log_softmax(self.head(input), axis=-1)
        parts = [head_out[..., :self.shortlist_size]]
        for i, tail in enumerate(self.tail):
            cluster_lp = F.log_softmax(tail(input), axis=-1)
            gate = head_out[..., self.shortlist_size + i:
                            self.shortlist_size + i + 1]
            parts.append(cluster_lp + gate)
        from ..ops import manipulation as _m
        return _m.concat(parts, axis=-1)

    def forward(self, input, label):
        """Target log-probs + NLL loss WITHOUT materializing the full
        [batch, n_classes] distribution: the head and each (narrow) tail
        projection are computed densely — XLA's static-shape answer to
        the reference's per-cluster row gathering — but only the target
        entry of each is gathered and masked in."""
        from ..core.dispatch import apply_op
        head_lp = F.log_softmax(self.head(input), axis=-1)
        cluster_lps = [F.log_softmax(t(input), axis=-1)
                       for t in self.tail]
        c = self.cutoffs
        short = self.shortlist_size

        def fn(hlp, lab, *clps):
            lab = lab.astype(jnp.int32)
            sl = jnp.clip(lab, 0, short - 1)
            out = jnp.take_along_axis(hlp, sl[..., None],
                                      axis=-1)[..., 0]
            for i, clp in enumerate(clps):
                rel = jnp.clip(lab - c[i], 0, clp.shape[-1] - 1)
                val = jnp.take_along_axis(clp, rel[..., None],
                                          axis=-1)[..., 0] \
                    + hlp[..., short + i]
                out = jnp.where((lab >= c[i]) & (lab < c[i + 1]), val,
                                out)
            return out

        output = apply_op("adaptive_log_softmax", fn,
                          tuple([head_lp, label] + cluster_lps))
        loss = -output.mean()
        return output, loss

    def log_prob(self, input):
        return self._full_log_prob(input)

    def predict(self, input):
        lp = self._full_log_prob(input)
        return lp.argmax(axis=-1)


class HSigmoidLoss(Layer):
    """Parity: paddle.nn.HSigmoidLoss (loss.py) — hierarchical sigmoid
    over the default complete binary tree or a custom path table."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self._num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr)
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_classes - 1], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes,
                               self.weight, self.bias,
                               path_table=path_table,
                               path_code=path_code)


class RNNTLoss(Layer):
    """Parity: paddle.nn.RNNTLoss (loss.py)."""

    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class FractionalMaxPool3D(Layer):
    """Parity: paddle.nn.FractionalMaxPool3D (pooling.py)."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._kernel_size = kernel_size
        self._random_u = random_u
        self._return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self._output_size,
                                       self._kernel_size,
                                       self._random_u,
                                       return_mask=self._return_mask)
