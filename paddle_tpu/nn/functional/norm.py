"""Normalization ops.

Parity: python/paddle/nn/functional/norm.py (reference), fused rms_norm from
paddle/phi/kernels/fusion/ (reference #17).  XLA fuses these; a Pallas
rms_norm kernel is wired in via FLAGS_use_pallas_kernels for the hot path.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ...ops._helpers import targ


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Parity: F.batch_norm. In training mode the running stats are updated
    in place on the provided tensors (host-side, eager) like the reference's
    mean/variance out params."""
    channel_axis = 1 if data_format.startswith("NC") else -1
    use_stats = (not training) if use_global_stats is None \
        else use_global_stats

    def fn(v, mean, var, *wb):
        axes = tuple(i for i in range(v.ndim)
                     if i != (channel_axis % v.ndim))
        if use_stats:
            m, s2 = mean, var
        else:
            m = jnp.mean(v, axis=axes)
            s2 = jnp.var(v, axis=axes)
        shape = [1] * v.ndim
        shape[channel_axis % v.ndim] = v.shape[channel_axis % v.ndim]
        out = (v - m.reshape(shape)) * jax.lax.rsqrt(
            s2.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    wb = tuple(targ(t) for t in (weight, bias) if t is not None)
    out = apply_op("batch_norm", fn,
                   (x, targ(running_mean), targ(running_var)) + wb)

    # Running-stat update rules:
    # - eager input: concrete update, as before.
    # - traced input with the buffer BOUND to a tracer (StaticFunction's
    #   bind_state): assign the traced update; StaticFunction collects it as
    #   an extra output and writes it back after the step.
    # - traced input with a CONCRETE buffer (layer unknown to the trace):
    #   skip — assigning a tracer to a host tensor would leak it.
    x_traced = isinstance(x, Tensor) and \
        isinstance(x._value, jax.core.Tracer)
    buf_traced = isinstance(running_mean, Tensor) and \
        isinstance(running_mean._value, jax.core.Tracer)
    if training and not use_stats and isinstance(running_mean, Tensor) \
            and isinstance(x, Tensor) and (not x_traced or buf_traced):
        axes = tuple(i for i in range(x._value.ndim)
                     if i != (channel_axis % x._value.ndim))
        m = jnp.mean(x._value, axis=axes)
        v2 = jnp.var(x._value, axis=axes)
        n = float(np.prod([x._value.shape[a] for a in axes]))
        unbiased = v2 * (n / max(n - 1.0, 1.0))
        running_mean._value = (momentum * running_mean._value
                               + (1 - momentum) * m).astype(
                                   running_mean._value.dtype)
        running_var._value = (momentum * running_var._value
                              + (1 - momentum) * unbiased).astype(
                                  running_var._value.dtype)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(normalized_shape)

    def fn(v, *wb):
        axes = tuple(range(v.ndim - nd, v.ndim))
        m = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - m) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]; i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    wb = tuple(targ(t) for t in (weight, bias) if t is not None)
    return apply_op("layer_norm", fn, (x,) + wb)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """Fused RMSNorm (parity: reference fused_rms_norm,
    paddle/phi/kernels/fusion/ #17).  Stats in fp32 for bf16 inputs."""
    def fn(v, *w):
        compute = v.astype(jnp.float32) if v.dtype in (jnp.bfloat16,
                                                       jnp.float16) else v
        ms = jnp.mean(jnp.square(compute), axis=-1, keepdims=True)
        out = compute * jax.lax.rsqrt(ms + epsilon)
        out = out.astype(v.dtype)
        if w:
            out = out * w[0]
        return out

    wb = (targ(weight),) if weight is not None else ()
    return apply_op("rms_norm", fn, (x,) + wb)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")

    def fn(v, *extra):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        i = 0
        if not use_input_stats:
            if running_mean is None or running_var is None:
                raise ValueError(
                    "use_input_stats=False requires running_mean/var")
            m = extra[i].reshape(1, -1, *([1] * (v.ndim - 2))); i += 1
            var = extra[i].reshape(1, -1, *([1] * (v.ndim - 2))); i += 1
        else:
            if running_mean is not None:
                i += 2  # skip running stats operands
            axes = tuple(range(2, v.ndim))
            m = jnp.mean(v, axis=axes, keepdims=True)
            var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - m) * jax.lax.rsqrt(var + eps)
        shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
        if weight is not None:
            out = out * extra[i].reshape(shape); i += 1
        if bias is not None:
            out = out + extra[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    operands = []
    if running_mean is not None:
        operands += [targ(running_mean), targ(running_var)]
    operands += [targ(t) for t in (weight, bias) if t is not None]
    return apply_op("instance_norm", fn, (x, *operands))


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")

    def fn(v, *wb):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        N, C = v.shape[0], v.shape[1]
        g = v.reshape((N, num_groups, C // num_groups) + v.shape[2:])
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        shape = [1, C] + [1] * (v.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    wb = tuple(targ(t) for t in (weight, bias) if t is not None)
    return apply_op("group_norm", fn, (x,) + wb)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def fn(v):
        sq = jnp.square(v)
        half = size // 2
        ch_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        c = v.shape[ch_axis]
        acc = jnp.zeros_like(v)
        for offset in range(-half, size - half):
            sl_src = [np.s_[:]] * v.ndim
            lo = max(0, -offset)
            hi = min(c, c - offset)
            sl_src[ch_axis] = np.s_[lo + offset:hi + offset]
            sl_dst = [np.s_[:]] * v.ndim
            sl_dst[ch_axis] = np.s_[lo:hi]
            pad_cfg = [(0, 0)] * v.ndim
            pad_cfg[ch_axis] = (lo, c - hi)
            acc = acc + jnp.pad(sq[tuple(sl_src)], pad_cfg)
        return v / jnp.power(k + alpha * acc / size, beta)
    return apply_op("local_response_norm", fn, (x,))
