"""Common functional ops: linear, dropout, embedding, attention, etc.

Parity: python/paddle/nn/functional/common.py + input.py (reference);
flash_attention parity: python/paddle/nn/functional/flash_attention.py:146
(reference #18) — here a fused softmax(QK^T)V with optional Pallas flash
kernel on TPU (see paddle_tpu/ops/pallas_kernels.py).
"""
from __future__ import annotations

import math as _math

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ...core.flags import get_flag
from ...ops._helpers import targ, wrap
from ...ops.random import next_key
from ...ops import manipulation as _manip

pad = _manip.pad  # re-export paddle.nn.functional.pad


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b); W is [in, out] (parity: F.linear, phi matmul+add —
    one MXU dot under XLA)."""
    def fn(v, w, *b):
        out = jnp.matmul(v, w)
        if b:
            out = out + b[0]
        return out
    args = (x, targ(weight)) + ((targ(bias),) if bias is not None else ())
    return apply_op("linear", fn, args)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and p > 0.0 and not training:
            return apply_op("dropout_infer_scale",
                            lambda v: (v * (1.0 - p)).astype(v.dtype), (x,))
        return x if isinstance(x, Tensor) else wrap(targ(x))
    # key passed as a visible arg (not a closure) so jit/sot recording can
    # substitute a fresh key per replay
    def fn(v, key):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(v.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return apply_op("dropout", fn, (x, next_key()))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    def fn(v, key):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / _math.sqrt((1 - p) * (1 + p * alpha_p ** 2))) \
            if p < 1 else 0.0
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return apply_op("alpha_dropout", fn, (x, next_key()))


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Parity: F.embedding (phi embedding kernel). A gather on TPU."""
    def fn(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op("embedding", fn, (targ(x), weight))


def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _oh
    return _oh(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k
    args = (label,) + ((targ(prior_dist),) if prior_dist is not None else ())
    return apply_op("label_smooth", fn, args)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply_op("cosine_similarity", fn, (x1, targ(x2)))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        n = jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=axis,
                              keepdims=True), 1.0 / p)
        return v / jnp.maximum(n, epsilon)
    return apply_op("normalize", fn, (x,))


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *bs):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bs:
            out = out + bs[0]
        return out
    args = (x1, targ(x2), targ(weight)) + (
        (targ(bias),) if bias is not None else ())
    return apply_op("bilinear", fn, args)


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Parity: paddle.nn.functional.scaled_dot_product_attention.
    Inputs [batch, seq, heads, head_dim] (paddle layout).  Uses the Pallas
    flash kernel on TPU when enabled, else an XLA-fused reference path."""
    use_dropout = dropout_p > 0.0 and training
    if get_flag("use_pallas_kernels") and not use_dropout:
        try:
            from ...ops.pallas_kernels import flash_attention_tpu
            return flash_attention_tpu(query, key, value, attn_mask,
                                       is_causal)
        except Exception:
            pass  # fall back to XLA path

    def fn(q, k, v, *m):
        # trailing arg is the dropout key when use_dropout (visible arg so
        # jit/sot replay re-randomizes; see dropout above)
        drop_key = None
        if use_dropout:
            drop_key, m = m[-1], m[:-1]
        # BSHD -> BHSD
        q_ = jnp.swapaxes(q, 1, 2)
        k_ = jnp.swapaxes(k, 1, 2)
        v_ = jnp.swapaxes(v, 1, 2)
        scale = 1.0 / _math.sqrt(q.shape[-1])
        logits = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * scale
        logits = logits.astype(jnp.float32)
        if is_causal:
            sq, sk = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
            logits = jnp.where(causal, logits, -jnp.inf)
        if m:
            mask = m[0]
            if mask.dtype == jnp.bool_:
                logits = jnp.where(mask, logits, -jnp.inf)
            else:
                logits = logits + mask.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        if use_dropout:
            keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p,
                                        probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p),
                              0.0).astype(probs.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v_)
        return jnp.swapaxes(out, 1, 2)

    args = (query, targ(key), targ(value)) + (
        (targ(attn_mask),) if attn_mask is not None else ())
    if use_dropout:
        args = args + (next_key(),)
    return apply_op("scaled_dot_product_attention", fn, args)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """Parity: F.flash_attention (reference
    python/paddle/nn/functional/flash_attention.py:146).  Returns
    (out, softmax) like the reference."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    return out, None


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (parity: F.unfold)."""
    from .conv import _pair
    k = _pair(kernel_sizes, 2)
    s = _pair(strides, 2)
    p = _pair(paddings, 2)
    d = _pair(dilations, 2)

    def fn(v):
        N, C, H, W = v.shape
        vp = jnp.pad(v, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        oh = (H + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (W + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = []
        for i in range(k[0]):
            for j in range(k[1]):
                patch = jax.lax.slice(
                    vp, (0, 0, i * d[0], j * d[1]),
                    (N, C, i * d[0] + (oh - 1) * s[0] + 1,
                     j * d[1] + (ow - 1) * s[1] + 1),
                    (1, 1, s[0], s[1]))
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # N,C,k*k,oh,ow
        return out.reshape(N, C * k[0] * k[1], oh * ow)
    return apply_op("unfold", fn, (x,))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    from .conv import _pair
    out_sz = _pair(output_sizes, 2)
    k = _pair(kernel_sizes, 2)
    s = _pair(strides, 2)
    p = _pair(paddings, 2)
    d = _pair(dilations, 2)

    def fn(v):
        N = v.shape[0]
        C = v.shape[1] // (k[0] * k[1])
        H, W = out_sz
        oh = (H + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (W + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        vr = v.reshape(N, C, k[0], k[1], oh, ow)
        out = jnp.zeros((N, C, H + 2 * p[0], W + 2 * p[1]), v.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                patch = vr[:, :, i, j]
                out = out.at[:, :,
                             i * d[0]:i * d[0] + (oh - 1) * s[0] + 1:s[0],
                             j * d[1]:j * d[1] + (ow - 1) * s[1] + 1:s[1]
                             ].add(patch)
        return out[:, :, p[0]:p[0] + H, p[1]:p[1] + W]
    return apply_op("fold", fn, (x,))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            N, C, H, W = v.shape
            out = v.reshape(N, C // (r * r), r, r, H, W)
            out = out.transpose(0, 1, 4, 2, 5, 3)
            return out.reshape(N, C // (r * r), H * r, W * r)
        N, H, W, C = v.shape
        out = v.reshape(N, H, W, r, r, C // (r * r))
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(N, H * r, W * r, C // (r * r))
    return apply_op("pixel_shuffle", fn, (x,))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(v):
        if data_format == "NCHW":
            N, C, H, W = v.shape
            out = v.reshape(N, C, H // r, r, W // r, r)
            out = out.transpose(0, 1, 3, 5, 2, 4)
            return out.reshape(N, C * r * r, H // r, W // r)
        N, H, W, C = v.shape
        out = v.reshape(N, H // r, r, W // r, r, C)
        out = out.transpose(0, 2, 4, 1, 3, 5)
        return out.reshape(N, H // r, W // r, C * r * r)
    return apply_op("pixel_unshuffle", fn, (x,))


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """Parity: F.interpolate.

    nearest/linear/bilinear/trilinear/bicubic via jax.image (half-pixel);
    ``align_corners=True`` uses explicit corner-aligned coordinate mapping
    through jax.image.scale_and_translate; ``area`` = adaptive average
    pooling (matching the reference's area semantics).
    """
    channel_last = not data_format.startswith("NC")
    if mode == "area":
        from .pooling import (adaptive_avg_pool1d, adaptive_avg_pool2d,
                              adaptive_avg_pool3d)
        if size is not None:
            sz = tuple(size) if isinstance(size, (list, tuple)) else (size,)
        else:
            xv = x._value if hasattr(x, "_value") else x
            spatial = xv.shape[1:-1] if channel_last else xv.shape[2:]
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(spatial)
            sz = tuple(int(s * f) for s, f in zip(spatial, sf))
        if channel_last:
            raise NotImplementedError(
                "mode='area' supports channel-first layouts only")
        pool = {1: adaptive_avg_pool1d, 2: adaptive_avg_pool2d,
                3: adaptive_avg_pool3d}[len(sz)]
        return pool(x, sz if len(sz) > 1 else sz[0])

    def fn(v):
        nd = v.ndim - 2
        spatial = v.shape[1:-1] if channel_last else v.shape[2:]
        if size is not None:
            tgt = tuple(int(s) for s in
                        (size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * nd
            tgt = tuple(int(s * f) for s, f in zip(spatial, sf))
        if channel_last:
            full = (v.shape[0],) + tgt + (v.shape[-1],)
            sp_dims = tuple(range(1, 1 + nd))
        else:
            full = v.shape[:2] + tgt
            sp_dims = tuple(range(2, 2 + nd))
        method = {"nearest": "nearest", "bilinear": "bilinear",
                  "trilinear": "trilinear", "bicubic": "bicubic",
                  "linear": "linear"}[mode]
        if align_corners and mode != "nearest":
            # corner-aligned mapping: in-coord = out-coord*(in-1)/(out-1),
            # i.e. scale s = (out-1)/(in-1) with translation 0.5*(1-s)
            # (pixel-center convention; calibrated against the reference)
            scales = jnp.array(
                [(o - 1) / (i - 1) if i > 1 else 1.0
                 for i, o in zip(spatial, tgt)], jnp.float32)
            trans = 0.5 * (1.0 - scales)
            out = jax.image.scale_and_translate(
                v.astype(jnp.float32), full, sp_dims, scales, trans,
                method="linear" if method in ("linear", "bilinear",
                                              "trilinear") else method,
                antialias=False)
            return out.astype(v.dtype)
        return jax.image.resize(v, full, method=method).astype(v.dtype)
    return apply_op("interpolate", fn, (x,))


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Parity: reference nn/functional/common.py:1802 — pad H/W with
    zeros; ``padding`` = int | [left, right, top, bottom] | Tensor."""
    if hasattr(padding, "numpy"):
        padding = padding.numpy().tolist()
    if isinstance(padding, (int, np.integer)):
        padding = [padding] * 4
    return pad(x, [int(p) for p in padding], mode="constant", value=0.0,
               data_format=data_format)
