"""Pooling ops via lax.reduce_window.

Parity: python/paddle/nn/functional/pooling.py (reference; phi pool
kernels).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import apply_op
from ...ops._helpers import targ
from .conv import _pair, _padding


def _window(nd, k, s, pad, channel_last, v_ndim):
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = [(0, 0)] + list(pad) + [(0, 0)]
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
        pads = [(0, 0), (0, 0)] + list(pad)
    return dims, strides, pads


def _pool(name, nd, x, kernel_size, stride, padding, mode, data_format,
          ceil_mode=False, exclusive=True):
    channel_last = not data_format.startswith("NC")
    k = _pair(kernel_size, nd)
    s = _pair(stride if stride is not None else kernel_size, nd)
    pad = _padding(padding, nd, data_format)

    def fn(v):
        if isinstance(pad, str):
            # lax.reduce_window accepts 'SAME'/'VALID' directly
            dims, strides, _ = _window(nd, k, s, [(0, 0)] * nd,
                                       channel_last, v.ndim)
            pads = pad
        else:
            eff = [list(p) for p in pad]
            if ceil_mode:
                sp0 = 1 if channel_last else 2
                for i in range(nd):
                    total = v.shape[sp0 + i] + eff[i][0] + eff[i][1]
                    out_n = -(-(total - k[i]) // s[i]) + 1
                    eff[i][1] += max(0, (out_n - 1) * s[i] + k[i] - total)
            dims, strides, pads = _window(nd, k, s,
                                          [tuple(p) for p in eff],
                                          channel_last, v.ndim)
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) \
                else jnp.iinfo(v.dtype).min
            return lax.reduce_window(v, init, lax.max, dims, strides, pads)
        # avg
        summed = lax.reduce_window(v, 0.0, lax.add, dims, strides, pads)
        padded = pads == "SAME" if isinstance(pads, str) \
            else any(p != (0, 0) for p in pads)
        if exclusive and padded:
            ones = jnp.ones_like(v)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                       pads)
            return summed / counts
        return summed / float(np.prod(k))

    return apply_op(name, fn, (x,))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    if return_mask:
        if df != "NCW":
            raise ValueError("return_mask requires NCL/NCW")
        return _max_pool_nd_with_mask(x, 1, kernel_size, stride, padding,
                                      ceil_mode)
    return _pool("max_pool1d", 1, x, kernel_size, stride, padding, "max", df,
                 ceil_mode=ceil_mode)


_SPATIAL_LAYOUT = {1: "NCW", 2: "NCHW", 3: "NCDHW"}
_FILTER_LAYOUT = {1: "OIW", 2: "OIHW", 3: "OIDHW"}


def _max_pool_nd_with_mask(x, nd, kernel_size, stride, padding,
                           ceil_mode=False):
    """Max pool returning (out, mask) where mask holds flat indices into
    the input spatial map — the reference's max_pool_with_index contract
    (phi pooling kernels) consumed by max_unpool{1,2,3}d."""
    k = _pair(kernel_size, nd)
    s = _pair(stride if stride is not None else kernel_size, nd)
    pad = _padding(padding, nd, _SPATIAL_LAYOUT[nd])
    if isinstance(pad, str):
        raise ValueError("return_mask requires explicit int padding")
    pad = [list(p) for p in pad]

    def fn(v):
        n, c = v.shape[0], v.shape[1]
        in_sp = v.shape[2:]
        if ceil_mode:
            # extra trailing -inf padding so partial windows count
            for i, sz in enumerate(in_sp):
                total = sz + pad[i][0] + pad[i][1]
                out_n = -(-(total - k[i]) // s[i]) + 1
                pad[i][1] += max(0, (out_n - 1) * s[i] + k[i] - total)
        neg = jnp.finfo(v.dtype).min if jnp.issubdtype(
            v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
        vp = jnp.pad(v, [(0, 0), (0, 0)] + [tuple(p) for p in pad],
                     constant_values=neg)
        # unroll window taps into the channel dim, then argmax over taps
        patches = jax.lax.conv_general_dilated_patches(
            vp, filter_shape=k, window_strides=s, padding="VALID",
            dimension_numbers=(_SPATIAL_LAYOUT[nd], _FILTER_LAYOUT[nd],
                               _SPATIAL_LAYOUT[nd]))
        out_sp = patches.shape[-nd:]
        taps = int(np.prod(k))
        patches = patches.reshape((n, c, taps) + out_sp)
        out = patches.max(axis=2)
        tap = patches.argmax(axis=2)                  # [N,C,*out_sp]
        # decompose the tap index into per-dim offsets, then rebuild the
        # flat input index (row-major over the UNPADDED spatial dims)
        flat = jnp.zeros_like(tap)
        rem = tap
        for i in range(nd):
            stride_i = int(np.prod(k[i + 1:]))
            d_i = rem // stride_i
            rem = rem % stride_i
            base = jnp.arange(out_sp[i]) * s[i] - pad[i][0]
            shape = [1] * (2 + nd)
            shape[2 + i] = out_sp[i]
            pos = base.reshape(shape) + d_i
            flat = flat * in_sp[i] + pos
        return out, flat.astype(jnp.int32)

    return apply_op(f"max_pool{nd}d_with_mask", fn, (x,))


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW":
            raise ValueError("return_mask requires NCHW")
        return _max_pool_nd_with_mask(x, 2, kernel_size, stride, padding,
                                      ceil_mode)
    return _pool("max_pool2d", 2, x, kernel_size, stride, padding, "max",
                 data_format, ceil_mode=ceil_mode)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Scatter pooled values back to their argmax positions.

    Parity: reference nn/functional/pooling.py:872 (max_unpool2d; phi
    unpool kernel): ``indices`` are flat h*W+w positions as produced by
    ``max_pool2d(..., return_mask=True)``."""
    if data_format != "NCHW":
        raise ValueError("max_unpool2d supports NCHW only")
    return _max_unpool_nd(x, indices, 2, kernel_size, stride, padding,
                          output_size, "max_unpool2d")


def _fractional_edges(in_sz, out_sz, pool_sz, u):
    """Per-output-cell [start, end) in input coords — mirrors the
    reference's FractionalStartIndex/EndIndex + FractionalRationalU
    (paddle/phi/kernels/funcs/pooling.h:106-140)."""
    alpha = float(in_sz - pool_sz) / max(
        out_sz - (1 if pool_sz > 0 else 0), 1)
    if pool_sz > 0:
        uu = u
    else:
        alpha = float(in_sz) / out_sz
        base = in_sz // out_sz
        u_max1 = (base + 2) / alpha - 1
        u_max2 = (in_sz + 1 - base) / alpha - (out_sz - 1)
        uu = u * min(u_max1, u_max2)
    edges = []
    for i in range(out_sz):
        start = int((i + uu) * alpha) - int(uu * alpha)
        end = start + pool_sz if pool_sz > 0 \
            else int((i + 1 + uu) * alpha) - int(uu * alpha)
        edges.append((max(start, 0), min(end, in_sz)))
    return edges


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Fractional max pooling (Graham 2015).

    Parity: reference nn/functional/pooling.py:2092 (phi
    FractionalMaxPool2dFunctor, funcs/pooling.cc): pseudo-random region
    boundaries from a single u in (0,1), optional fixed kernel."""
    out_sz = _pair(output_size, 2)
    k = _pair(kernel_size, 2) if kernel_size is not None else (0, 0)
    if random_u is None:
        # framework RNG (paddle.seed-reproducible), not np.random; u must
        # be a host float because region edges are static shapes
        from ...ops.random import next_key
        key = next_key()
        key = key._value if hasattr(key, "_value") else key
        if isinstance(key, jax.core.Tracer):
            raise ValueError(
                "fractional_max_pool2d(random_u=None) cannot draw its "
                "region offset inside jit/to_static (the pooling regions "
                "are static shapes); pass an explicit random_u")
        u = float(jax.random.uniform(key, ()))
    else:
        u = float(random_u)

    def fn(v):
        n, c, h, w = v.shape
        h_edges = _fractional_edges(h, out_sz[0], k[0], u)
        w_edges = _fractional_edges(w, out_sz[1], k[1], u)
        # one padded gather over precomputed flat indices (static region
        # edges), not a per-cell python loop — O(1) ops in the trace
        maxlen = max((he - hs) * (we - ws)
                     for hs, he in h_edges for ws, we in w_edges)
        idx = np.zeros((out_sz[0], out_sz[1], maxlen), np.int32)
        valid = np.zeros((out_sz[0], out_sz[1], maxlen), bool)
        for i, (hs, he) in enumerate(h_edges):
            for j, (ws, we) in enumerate(w_edges):
                cell = (np.arange(hs, he)[:, None] * w
                        + np.arange(ws, we)[None, :]).ravel()
                idx[i, j, :cell.size] = cell
                valid[i, j, :cell.size] = True
        gi = jnp.asarray(idx.reshape(-1))              # [OH*OW*maxlen]
        gv = jnp.asarray(valid.reshape(1, 1, -1))
        neg = jnp.finfo(v.dtype).min if jnp.issubdtype(
            v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
        flat = v.reshape(n, c, h * w)
        g = jnp.where(gv, flat[:, :, gi], neg).reshape(
            n, c, out_sz[0], out_sz[1], maxlen)
        out = g.max(axis=-1)
        if return_mask:
            a = g.argmax(axis=-1)                      # [N,C,OH,OW]
            gidx = jnp.asarray(idx)                    # [OH,OW,maxlen]
            mask = jnp.take_along_axis(
                jnp.broadcast_to(gidx, (n, c) + gidx.shape),
                a[..., None], axis=-1)[..., 0]
            return out, mask.astype(jnp.int32)
        return out

    return apply_op("fractional_max_pool2d", fn, (x,))


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if data_format != "NCDHW":
            raise ValueError("return_mask requires NCDHW")
        return _max_pool_nd_with_mask(x, 3, kernel_size, stride, padding,
                                      ceil_mode)
    return _pool("max_pool3d", 3, x, kernel_size, stride, padding, "max",
                 data_format, ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool("avg_pool1d", 1, x, kernel_size, stride, padding, "avg", df,
                 ceil_mode=ceil_mode, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool("avg_pool2d", 2, x, kernel_size, stride, padding, "avg",
                 data_format, ceil_mode=ceil_mode, exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool("avg_pool3d", 3, x, kernel_size, stride, padding, "avg",
                 data_format, ceil_mode=ceil_mode, exclusive=exclusive)


def _adaptive_pool(name, nd, x, output_size, mode, data_format):
    channel_last = not data_format.startswith("NC")
    # None entries mean "keep this axis's input size" (reference
    # adaptive_avg_pool2d contract) — _pair would int()-crash on them
    if isinstance(output_size, (list, tuple)):
        out_sz = tuple(None if s is None else int(s)
                       for s in output_size)
        if len(out_sz) != nd:
            out_sz = out_sz * nd
    else:
        out_sz = (int(output_size),) * nd

    def fn(v):
        spatial_axes = list(range(2, 2 + nd)) if not channel_last \
            else list(range(1, 1 + nd))
        out = v
        for i, ax in enumerate(spatial_axes):
            if out_sz[i] is None:
                continue
            in_s = out.shape[ax]
            o = out_sz[i]
            if in_s % o == 0:
                # even split: reshape + reduce
                k = in_s // o
                new_shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                r = out.reshape(new_shape)
                out = r.max(axis=ax + 1) if mode == "max" \
                    else r.mean(axis=ax + 1)
            else:
                # uneven: gather per output bin
                pieces = []
                for j in range(o):
                    lo = (j * in_s) // o
                    hi = -(-((j + 1) * in_s) // o)
                    sl = [np.s_[:]] * out.ndim
                    sl[ax] = np.s_[lo:hi]
                    piece = out[tuple(sl)]
                    red = piece.max(axis=ax, keepdims=True) if mode == "max" \
                        else piece.mean(axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply_op(name, fn, (x,))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool("adaptive_avg_pool1d", 1, x, output_size, "avg",
                          "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool("adaptive_avg_pool2d", 2, x, output_size, "avg",
                          data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool("adaptive_avg_pool3d", 3, x, output_size, "avg",
                          data_format)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool2d(return_mask=True) — use max_pool2d with "
            "explicit kernel/stride for indices")
    return _adaptive_pool("adaptive_max_pool2d", 2, x, output_size, "max",
                          "NCHW")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool1d(return_mask=True) — use max_pool1d with "
            "explicit kernel/stride for indices")
    return _adaptive_pool("adaptive_max_pool1d", 1, x, output_size, "max",
                          "NCW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool3d(return_mask=True) — use max_pool3d with "
            "explicit kernel/stride for indices")
    return _adaptive_pool("adaptive_max_pool3d", 3, x, output_size, "max",
                          "NCDHW")


def _max_unpool_nd(x, indices, nd, kernel_size, stride, padding,
                   output_size, op_name):
    """Shared 1/2/3-D unpool: scatter values to flat spatial indices."""
    k = _pair(kernel_size, nd)
    s = _pair(stride if stride is not None else kernel_size, nd)
    p = _pair(padding, nd)

    def fn(v, idx):
        n, c = v.shape[0], v.shape[1]
        in_sp = v.shape[2:]
        if output_size is None:
            out_sp = tuple((in_sp[i] - 1) * s[i] - 2 * p[i] + k[i]
                           for i in range(nd))
        else:
            out_sp = tuple(int(t) for t in output_size[-nd:])
        total = int(np.prod(out_sp))
        flat = jnp.zeros((n, c, total), v.dtype)
        bi = jnp.arange(n)[:, None, None]
        ci = jnp.arange(c)[None, :, None]
        flat = flat.at[bi, ci, idx.reshape(n, c, -1)].set(
            v.reshape(n, c, -1))
        return flat.reshape((n, c) + out_sp)

    return apply_op(op_name, fn, (x, targ(indices)))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Parity: paddle.nn.functional.max_unpool1d (phi unpool kernel)."""
    if data_format != "NCL":
        raise ValueError("max_unpool1d supports NCL only")
    return _max_unpool_nd(x, indices, 1, kernel_size, stride, padding,
                          output_size, "max_unpool1d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Parity: paddle.nn.functional.max_unpool3d (phi unpool3d kernel);
    indices are flat d*H*W + h*W + w positions."""
    if data_format != "NCDHW":
        raise ValueError("max_unpool3d supports NCDHW only")
    return _max_unpool_nd(x, indices, 3, kernel_size, stride, padding,
                          output_size, "max_unpool3d")
