"""Pooling ops via lax.reduce_window.

Parity: python/paddle/nn/functional/pooling.py (reference; phi pool
kernels).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import apply_op
from .conv import _pair, _padding


def _window(nd, k, s, pad, channel_last, v_ndim):
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = [(0, 0)] + list(pad) + [(0, 0)]
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
        pads = [(0, 0), (0, 0)] + list(pad)
    return dims, strides, pads


def _pool(name, nd, x, kernel_size, stride, padding, mode, data_format,
          ceil_mode=False, exclusive=True):
    channel_last = not data_format.startswith("NC")
    k = _pair(kernel_size, nd)
    s = _pair(stride if stride is not None else kernel_size, nd)
    pad = _padding(padding, nd, data_format)

    def fn(v):
        if isinstance(pad, str):
            # lax.reduce_window accepts 'SAME'/'VALID' directly
            dims, strides, _ = _window(nd, k, s, [(0, 0)] * nd,
                                       channel_last, v.ndim)
            pads = pad
        else:
            dims, strides, pads = _window(nd, k, s, pad, channel_last,
                                          v.ndim)
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) \
                else jnp.iinfo(v.dtype).min
            return lax.reduce_window(v, init, lax.max, dims, strides, pads)
        # avg
        summed = lax.reduce_window(v, 0.0, lax.add, dims, strides, pads)
        padded = pads == "SAME" if isinstance(pads, str) \
            else any(p != (0, 0) for p in pads)
        if exclusive and padded:
            ones = jnp.ones_like(v)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                       pads)
            return summed / counts
        return summed / float(np.prod(k))

    return apply_op(name, fn, (x,))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool("max_pool1d", 1, x, kernel_size, stride, padding, "max", df)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool("max_pool2d", 2, x, kernel_size, stride, padding, "max",
                data_format)
    if return_mask:
        # indices not natively produced by reduce_window; compute via argmax
        # over extracted patches (rarely used on TPU; correctness path).
        raise NotImplementedError("return_mask=True not supported yet")
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool("max_pool3d", 3, x, kernel_size, stride, padding, "max",
                 data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool("avg_pool1d", 1, x, kernel_size, stride, padding, "avg", df,
                 exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool("avg_pool2d", 2, x, kernel_size, stride, padding, "avg",
                 data_format, exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool("avg_pool3d", 3, x, kernel_size, stride, padding, "avg",
                 data_format, exclusive=exclusive)


def _adaptive_pool(name, nd, x, output_size, mode, data_format):
    channel_last = not data_format.startswith("NC")
    out_sz = _pair(output_size, nd)

    def fn(v):
        spatial_axes = list(range(2, 2 + nd)) if not channel_last \
            else list(range(1, 1 + nd))
        out = v
        for i, ax in enumerate(spatial_axes):
            if out_sz[i] is None:
                continue
            in_s = out.shape[ax]
            o = out_sz[i]
            if in_s % o == 0:
                # even split: reshape + reduce
                k = in_s // o
                new_shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                r = out.reshape(new_shape)
                out = r.max(axis=ax + 1) if mode == "max" \
                    else r.mean(axis=ax + 1)
            else:
                # uneven: gather per output bin
                pieces = []
                for j in range(o):
                    lo = (j * in_s) // o
                    hi = -(-((j + 1) * in_s) // o)
                    sl = [np.s_[:]] * out.ndim
                    sl[ax] = np.s_[lo:hi]
                    piece = out[tuple(sl)]
                    red = piece.max(axis=ax, keepdims=True) if mode == "max" \
                        else piece.mean(axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply_op(name, fn, (x,))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool("adaptive_avg_pool1d", 1, x, output_size, "avg",
                          "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool("adaptive_avg_pool2d", 2, x, output_size, "avg",
                          data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool("adaptive_avg_pool3d", 3, x, output_size, "avg",
                          data_format)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool("adaptive_max_pool2d", 2, x, output_size, "max",
                          "NCHW")
