"""Loss functions.

Parity: python/paddle/nn/functional/loss.py (reference; phi cross_entropy
kernels paddle/phi/kernels/funcs/cross_entropy.h).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ...ops._helpers import targ


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


@jax.custom_vjp
def _fused_softmax_xent(logits, safe_idx, valid):
    """Per-row softmax cross-entropy for hard labels over a large vocab.

    The custom VJP keeps the [N, V] working set in the input dtype: the
    forward's f32 math fuses into two streaming reductions (max, sumexp)
    with only the [N] lse row vector saved, and the backward emits
    (softmax - onehot)·g directly in the logits dtype — no f32 [N, V]
    log-prob residual, which for a 32k llama vocab is ~2 GB the naive
    log_softmax formulation kept alive per step (reference analog: the
    fused softmax_with_cross_entropy kernel,
    paddle/phi/kernels/funcs/cross_entropy.h)."""
    loss, _ = _fused_softmax_xent_fwd(logits, safe_idx, valid)
    return loss


def _fused_softmax_xent_fwd(logits, safe_idx, valid):
    xm = jnp.max(logits, axis=-1).astype(jnp.float32)
    s = jnp.sum(jnp.exp(logits.astype(jnp.float32) - xm[..., None]),
                axis=-1)
    lse = jnp.log(s) + xm
    picked = jnp.take_along_axis(
        logits, safe_idx[..., None], axis=-1)[..., 0].astype(jnp.float32)
    loss = jnp.where(valid, lse - picked, 0.0)
    return loss, (logits, safe_idx, valid, lse)


def _fused_softmax_xent_bwd(res, g):
    logits, safe_idx, valid, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = jax.nn.one_hot(safe_idx, logits.shape[-1],
                            dtype=jnp.float32)
    scale = (g * valid.astype(jnp.float32))[..., None]
    grad = ((p - onehot) * scale).astype(logits.dtype)
    return grad, None, None


_fused_softmax_xent.defvjp(_fused_softmax_xent_fwd,
                           _fused_softmax_xent_bwd)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Parity: F.cross_entropy (softmax+ce fused like the reference's
    softmax_with_cross_entropy kernel)."""
    def fn(logits, lab, *w):
        def make_logp():
            if use_softmax:
                return jax.nn.log_softmax(logits.astype(jnp.float32),
                                          axis=axis)
            return jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-10,
                                    1.0))
        C = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim
                          and lab.shape[axis] == C
                          and jnp.issubdtype(lab.dtype, jnp.floating)):
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / C
            loss = -jnp.sum(soft * make_logp(), axis=axis)
        else:
            li = lab
            if li.ndim == logits.ndim:
                li = jnp.squeeze(li, axis)
            li = li.astype(jnp.int32)
            valid = li != ignore_index
            safe = jnp.where(valid, li, 0)
            if use_softmax and axis in (-1, logits.ndim - 1) \
                    and label_smoothing == 0 and not w:
                # large-vocab fast path: fused kernel, no f32 residual
                loss = _fused_softmax_xent(logits, safe, valid)
                if reduction == "mean":
                    denom = jnp.maximum(
                        jnp.sum(valid.astype(jnp.float32)), 1.0)
                    return jnp.sum(loss) / denom
                return _reduce(loss, reduction)
            logp = make_logp()
            picked = jnp.take_along_axis(
                logp, safe[..., None], axis=axis).squeeze(axis)
            if label_smoothing > 0:
                smooth_term = jnp.mean(logp, axis=axis)
                picked = (1 - label_smoothing) * picked \
                    + label_smoothing * smooth_term
            loss = jnp.where(valid, -picked, 0.0)
            if w:
                wt = jnp.take(w[0].astype(jnp.float32), safe)
                loss = loss * jnp.where(valid, wt, 0.0)
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(valid, wt, 0.0))
                    return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)),
                                    1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = (input, targ(label)) + ((targ(weight),) if weight is not None
                                   else ())
    return apply_op("cross_entropy", fn, args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1, name=None):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # reference returns loss with a trailing 1-dim
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        from ..functional.activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def fn(p, lab, *w):
        p = jnp.clip(p.astype(jnp.float32), 1e-7, 1 - 1e-7)
        loss = -(lab * jnp.log(p) + (1 - lab) * jnp.log1p(-p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = (input, targ(label)) + ((targ(weight),) if weight is not None
                                   else ())
    return apply_op("binary_cross_entropy", fn, args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def fn(z, lab, *extra):
        z = z.astype(jnp.float32)
        lab = lab.astype(jnp.float32)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), pos_weight variant
        if pw is not None:
            log_w = (pw - 1) * lab + 1
            loss = (1 - lab) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z))
                                            + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0) - z * lab + jnp.logaddexp(
                0.0, -jnp.abs(z))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, targ(label)]
    if weight is not None:
        args.append(targ(weight))
    if pos_weight is not None:
        args.append(targ(pos_weight))
    return apply_op("bce_with_logits", fn, tuple(args))


def mse_loss(input, label, reduction="mean", name=None):
    def fn(a, b):
        return _reduce(jnp.square(a - b), reduction)
    return apply_op("mse_loss", fn, (input, targ(label)))


def l1_loss(input, label, reduction="mean", name=None):
    def fn(a, b):
        return _reduce(jnp.abs(a - b), reduction)
    return apply_op("l1_loss", fn, (input, targ(label)))


def square_error_cost(input, label, name=None):
    def fn(a, b):
        return jnp.square(a - b)
    return apply_op("square_error_cost", fn, (input, targ(label)))


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, lab):
        return -lab * jnp.log(p + epsilon) \
            - (1 - lab) * jnp.log(1 - p + epsilon)
    return apply_op("log_loss", fn, (input, targ(label)))


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def fn(logp, lab, *w):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=1).squeeze(1)
        loss = jnp.where(valid, -picked, 0.0)
        if w:
            wt = jnp.take(w[0], safe)
            loss = loss * jnp.where(valid, wt, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(jnp.where(valid, wt, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)
    args = (input, targ(label)) + ((targ(weight),) if weight is not None
                                   else ())
    return apply_op("nll_loss", fn, args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(logp, t):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            safe_t = jnp.clip(t, 1e-10, None)
            loss = t * (jnp.log(safe_t) - logp)
            loss = jnp.where(t > 0, loss, 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", fn, (input, targ(label)))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", fn, (input, targ(label)))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def fn(a, b, lab):
        return _reduce(jnp.maximum(0.0, -lab * (a - b) + margin), reduction)
    return apply_op("margin_ranking_loss", fn,
                    (input, targ(other), targ(label)))


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def fn(a, b, lab):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(lab == 1, 1 - cos,
                         jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", fn,
                    (input1, targ(input2), targ(label)))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def fn(a, lab):
        loss = jnp.where(lab == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply_op("hinge_embedding_loss", fn, (input, targ(label)))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def fn(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + epsilon, p),
                               -1), 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + epsilon, p),
                               -1), 1 / p)
        if swap:
            dpn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + epsilon,
                                              p), -1), 1 / p)
            dn = jnp.minimum(dn, dpn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply_op("triplet_margin_loss", fn,
                    (input, targ(positive), targ(negative)))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, lab, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * lab + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * lab + (1 - p) * (1 - lab)
        a_t = alpha * lab + (1 - alpha) * (1 - lab)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    args = (logit, targ(label)) + ((targ(normalizer),)
                                   if normalizer is not None else ())
    return apply_op("sigmoid_focal_loss", fn, args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC via the classic alpha recursion in log space (lax.scan)."""
    def fn(lp, lab, in_len, lab_len):
        # lp: [T, B, C] log-probs (paddle layout)
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        # extended labels with blanks
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = -1e30

        def get_probs(t_lp):
            return jnp.take_along_axis(
                t_lp[:, None, :].repeat(S, 1), ext[..., None],
                axis=-1).squeeze(-1)  # [B, S]

        init = jnp.full((B, S), neg_inf)
        init = init.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=-1)[:, 0]
        init = init.at[:, 1].set(first_lab)

        same = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t_lp):
            a0 = alpha
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf),
                                  alpha[:, :-1]], 1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf),
                                  alpha[:, :-2]], 1)
            a2 = jnp.where(same | (ext == blank), neg_inf, a2)
            merged = jnp.logaddexp(jnp.logaddexp(a0, a1), a2)
            new = merged + get_probs(t_lp)
            return new, new

        _, alphas = jax.lax.scan(step, init, lp[1:])
        alphas = jnp.concatenate([init[None], alphas], 0)  # [T,B,S]
        t_idx = (in_len.astype(jnp.int32) - 1)
        final = alphas[t_idx, jnp.arange(B)]  # [B,S]
        s_last = 2 * lab_len.astype(jnp.int32)
        ll_blank = jnp.take_along_axis(final, s_last[:, None], 1)[:, 0]
        ll_label = jnp.take_along_axis(
            final, jnp.maximum(s_last - 1, 0)[:, None], 1)[:, 0]
        ll = jnp.logaddexp(ll_blank, ll_label)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(
                lab_len.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return apply_op("ctc_loss", fn,
                    (log_probs, targ(labels), targ(input_lengths),
                     targ(label_lengths)))


def soft_margin_loss(input, label, reduction="mean", name=None):
    """Parity: reference nn/functional/loss.py:3999 —
    log(1 + exp(-label * input)) with label in {-1, 1}."""
    def fn(x, y):
        return _reduce(jax.nn.softplus(-y.astype(x.dtype) * x), reduction)
    return apply_op("soft_margin_loss", fn, (input, targ(label)))


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Parity: reference nn/functional/loss.py:3868 — multi-class hinge
    sum_j max(0, margin - x[y] + x[j])^p / C, j != y."""
    def fn(x, y, *w):
        C = x.shape[1]
        y = y.astype(jnp.int32)
        xy = jnp.take_along_axis(x, y[:, None], axis=1)       # [N, 1]
        h = jnp.maximum(0.0, margin - xy + x)
        if p != 1:
            h = jnp.power(h, p)
        if w:
            h = h * jnp.take_along_axis(
                w[0][None, :], y[:, None], axis=1)
        h = h * (1.0 - jax.nn.one_hot(y, C, dtype=x.dtype))
        return _reduce(jnp.sum(h, axis=1) / C, reduction)
    args = (input, targ(label)) + ((targ(weight),)
                                   if weight is not None else ())
    return apply_op("multi_margin_loss", fn, args)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """Parity: reference nn/functional/loss.py:3259 — per-class sigmoid
    BCE averaged over classes; label in {0, 1} (or {-1,1} mapped)."""
    def fn(x, y, *w):
        y = y.astype(x.dtype)
        # stable -(y*log sigma(x) + (1-y)*log sigma(-x))
        per = y * jax.nn.softplus(-x) + (1 - y) * jax.nn.softplus(x)
        if w:
            per = per * w[0]
        return _reduce(jnp.mean(per, axis=-1), reduction)
    args = (input, targ(label)) + ((targ(weight),)
                                   if weight is not None else ())
    return apply_op("multi_label_soft_margin_loss", fn, args)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """Parity: reference nn/functional/loss.py:1488 (phi
    poisson_nll_loss kernel)."""
    def fn(x, y):
        y = y.astype(x.dtype)
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(
                2.0 * np.pi * y)
            loss = loss + jnp.where(y > 1.0, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply_op("poisson_nll_loss", fn, (input, targ(label)))


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Parity: reference nn/functional/loss.py:4091."""
    def fn(mu, y, var):
        var = jnp.maximum(var.astype(mu.dtype), epsilon)
        loss = 0.5 * (jnp.log(var)
                      + jnp.square(y.astype(mu.dtype) - mu) / var)
        if full:
            loss = loss + 0.5 * np.log(2.0 * np.pi)
        return _reduce(loss, reduction)
    return apply_op("gaussian_nll_loss", fn,
                    (input, targ(label), targ(variance)))


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Parity: reference nn/functional/loss.py:39 — binary/seg dice over
    one-hot labels, reduced per-sample then averaged."""
    def fn(x, y):
        oh = jax.nn.one_hot(y[..., 0].astype(jnp.int32), x.shape[-1],
                            dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inse = jnp.sum(x * oh, axis=red)
        denom = jnp.sum(x, axis=red) + jnp.sum(oh, axis=red)
        return jnp.mean(1.0 - 2.0 * inse / (denom + epsilon))
    return apply_op("dice_loss", fn, (input, targ(label)))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Parity: reference nn/functional/loss.py:313 — similarity-matrix
    cross entropy + L2 regularizer on the embeddings."""
    def fn(a, p, lab):
        n = a.shape[0]
        lab = lab.reshape(n, 1).astype(a.dtype)
        eq = (lab == lab.T).astype(a.dtype)
        tgt = eq / jnp.sum(eq, axis=1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(jnp.square(a), 1))
              + jnp.mean(jnp.sum(jnp.square(p), 1))) * 0.25 * l2_reg
        sim = a @ p.T
        xent = -jnp.sum(tgt * jax.nn.log_softmax(sim, axis=-1), axis=-1)
        return jnp.mean(xent) + l2
    return apply_op("npair_loss", fn,
                    (anchor, targ(positive), targ(labels)))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax cross entropy.

    Parity: reference nn/functional/loss.py:2081 (phi
    margin_cross_entropy kernel): ``logits`` are cosine similarities;
    the target logit becomes cos(m1*theta + m2) - m3, everything is
    scaled by ``scale`` and fed through softmax CE.  The
    model-parallel ``group`` path of the reference is covered by
    ParallelCrossEntropy (mp_layers) in this framework; here the full
    class dim is assumed local."""
    if group is not None:
        raise NotImplementedError(
            "margin_cross_entropy(group=...) — use "
            "fleet.meta_parallel.ParallelCrossEntropy for class-sharded "
            "logits")

    def fn(x, y):
        y = y.astype(jnp.int32)
        xf = x.astype(jnp.float32)
        tgt = jnp.take_along_axis(xf, y[:, None], axis=1)[:, 0]
        if margin1 != 1.0 or margin2 != 0.0:
            theta = jnp.arccos(jnp.clip(tgt, -1.0, 1.0))
            tgt = jnp.cos(margin1 * theta + margin2)
        tgt = tgt - margin3
        mod = xf.at[jnp.arange(x.shape[0]), y].set(tgt) * scale
        logp = jax.nn.log_softmax(mod, axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=1)
        red = _reduce(loss, reduction)
        if return_softmax:
            return red, jnp.exp(logp).astype(x.dtype)
        return red
    return apply_op("margin_cross_entropy", fn, (logits, targ(label)))
