from .activation import (relu, relu6, relu_, gelu, silu, swish, softmax,
                         log_softmax, softplus, softsign, sigmoid, tanh,
                         hardtanh, hardsigmoid, hardswish, leaky_relu, elu,
                         celu, selu, mish, tanhshrink, softshrink, hardshrink,
                         prelu, glu, maxout, log_sigmoid, thresholded_relu,
                         rrelu, swiglu, gumbel_softmax)
from .common import (linear, dropout, dropout2d, dropout3d, alpha_dropout,
                     embedding, one_hot, pad, interpolate, upsample,
                     unfold, fold, pixel_shuffle, pixel_unshuffle,
                     label_smooth, cosine_similarity, normalize, bilinear,
                     flash_attention, scaled_dot_product_attention,
                     zeropad2d)
from .conv import (conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
                   conv3d_transpose)
from .pooling import (avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d,
                      max_pool2d, max_pool3d, adaptive_avg_pool1d,
                      adaptive_avg_pool2d, adaptive_avg_pool3d,
                      adaptive_max_pool2d, max_unpool2d,
                      fractional_max_pool2d, adaptive_max_pool1d,
                      adaptive_max_pool3d, max_unpool1d, max_unpool3d)
from .norm import (batch_norm, layer_norm, instance_norm, group_norm,
                   local_response_norm, rms_norm)
from .loss import (cross_entropy, softmax_with_cross_entropy,
                   binary_cross_entropy, binary_cross_entropy_with_logits,
                   mse_loss, l1_loss, nll_loss, kl_div, smooth_l1_loss,
                   margin_ranking_loss, cosine_embedding_loss, ctc_loss,
                   hinge_embedding_loss, triplet_margin_loss, log_loss,
                   square_error_cost, sigmoid_focal_loss,
                   soft_margin_loss, multi_margin_loss,
                   multi_label_soft_margin_loss, poisson_nll_loss,
                   gaussian_nll_loss, dice_loss, npair_loss,
                   margin_cross_entropy)
from .vision import (affine_grid, grid_sample, channel_shuffle,
                     temporal_shift)

# round-4 functional tail
from .extended import (pairwise_distance, triplet_margin_with_distance_loss,
                       hsigmoid_loss, rnnt_loss, class_center_sample,
                       fractional_max_pool3d)
from ...ops.op_surface import sequence_mask, gather_tree  # noqa: F401


def sparse_attention(query, key, value, sparse_csr_offset=None,
                     sparse_csr_columns=None, sparse_mask=None,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Parity: paddle.nn.functional.sparse_attention — delegates to the
    sparse-pattern attention kernel (paddle_tpu/sparse/nn/functional.py);
    accepts either a prebuilt sparse_mask or CSR offset/columns."""
    from ...sparse.nn.functional import attention as _attn
    from ...sparse import sparse_coo_tensor, sparse_csr_tensor
    if sparse_mask is None:
        if sparse_csr_offset is None or sparse_csr_columns is None:
            raise ValueError("pass sparse_mask or CSR offset/columns")
        import numpy as _np
        off = _np.asarray(sparse_csr_offset._value
                          if hasattr(sparse_csr_offset, "_value")
                          else sparse_csr_offset)
        cols = _np.asarray(sparse_csr_columns._value
                           if hasattr(sparse_csr_columns, "_value")
                           else sparse_csr_columns)
        S = query.shape[-2]
        if off.ndim >= 2:
            # reference layout: per-(batch, head) CSR [B, H, S+1] /
            # [B, H, nnz] -> one 3-D pattern indexed by b*H + h
            BH = int(_np.prod(off.shape[:-1]))
            off2 = off.reshape(BH, -1)
            cols2 = cols.reshape(BH, -1)
            bh_idx, row_idx, col_idx = [], [], []
            for bh in range(BH):
                counts = _np.diff(off2[bh])
                nnz = int(off2[bh, -1])
                bh_idx.append(_np.full(nnz, bh))
                row_idx.append(_np.repeat(_np.arange(S), counts))
                col_idx.append(cols2[bh, :nnz])
            idx = _np.stack([_np.concatenate(bh_idx),
                             _np.concatenate(row_idx),
                             _np.concatenate(col_idx)])
            sparse_mask = sparse_coo_tensor(
                idx, _np.ones(idx.shape[1], _np.float32), (BH, S, S))
        else:
            sparse_mask = sparse_csr_tensor(
                off.reshape(-1)[: S + 1], cols.reshape(-1),
                _np.ones(cols.size, _np.float32), (S, S))
    return _attn(query, key, value, sparse_mask,
                 key_padding_mask=key_padding_mask, attn_mask=attn_mask)


def _inplace_act(fn):
    def g(x, *a, **k):
        return x._inplace_assign(fn(x, *a, **k))
    g.__name__ = fn.__name__ + "_"
    return g


elu_ = _inplace_act(elu)
hardtanh_ = _inplace_act(hardtanh)
leaky_relu_ = _inplace_act(leaky_relu)
softmax_ = _inplace_act(softmax)
tanh_ = _inplace_act(tanh)
thresholded_relu_ = _inplace_act(thresholded_relu)
