from .activation import (relu, relu6, relu_, gelu, silu, swish, softmax,
                         log_softmax, softplus, softsign, sigmoid, tanh,
                         hardtanh, hardsigmoid, hardswish, leaky_relu, elu,
                         celu, selu, mish, tanhshrink, softshrink, hardshrink,
                         prelu, glu, maxout, log_sigmoid, thresholded_relu,
                         rrelu, swiglu, gumbel_softmax)
from .common import (linear, dropout, dropout2d, dropout3d, alpha_dropout,
                     embedding, one_hot, pad, interpolate, upsample,
                     unfold, fold, pixel_shuffle, pixel_unshuffle,
                     label_smooth, cosine_similarity, normalize, bilinear,
                     flash_attention, scaled_dot_product_attention,
                     zeropad2d)
from .conv import (conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
                   conv3d_transpose)
from .pooling import (avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d,
                      max_pool2d, max_pool3d, adaptive_avg_pool1d,
                      adaptive_avg_pool2d, adaptive_avg_pool3d,
                      adaptive_max_pool2d, max_unpool2d,
                      fractional_max_pool2d, adaptive_max_pool1d,
                      adaptive_max_pool3d, max_unpool1d, max_unpool3d)
from .norm import (batch_norm, layer_norm, instance_norm, group_norm,
                   local_response_norm, rms_norm)
from .loss import (cross_entropy, softmax_with_cross_entropy,
                   binary_cross_entropy, binary_cross_entropy_with_logits,
                   mse_loss, l1_loss, nll_loss, kl_div, smooth_l1_loss,
                   margin_ranking_loss, cosine_embedding_loss, ctc_loss,
                   hinge_embedding_loss, triplet_margin_loss, log_loss,
                   square_error_cost, sigmoid_focal_loss,
                   soft_margin_loss, multi_margin_loss,
                   multi_label_soft_margin_loss, poisson_nll_loss,
                   gaussian_nll_loss, dice_loss, npair_loss,
                   margin_cross_entropy)
from .vision import (affine_grid, grid_sample, channel_shuffle,
                     temporal_shift)
