"""Activation functions.

Parity: python/paddle/nn/functional/activation.py (reference; phi
activation kernels).  All fuse into adjacent ops under XLA.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ...ops.registry import register
from ...ops._helpers import targ
from ...ops import math as _m

tanh = _m.tanh
sigmoid = _m.sigmoid


def _act(name, jfn):
    def op(x, name=None):
        return apply_op(op.__op_name__, jfn, (x,))
    op.__op_name__ = name
    op.__name__ = name
    register(name, op, category="activation")
    return op


relu = _act("relu", jax.nn.relu)
relu6 = _act("relu6", jax.nn.relu6)
silu = _act("silu", jax.nn.silu)
softsign = _act("softsign", jax.nn.soft_sign)
log_sigmoid = _act("log_sigmoid", jax.nn.log_sigmoid)
tanhshrink = _act("tanhshrink", lambda x: x - jnp.tanh(x))
mish = _act("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))


def relu_(x, name=None):
    return x._inplace_assign(relu(x))


def gelu(x, approximate=False, name=None):
    return apply_op("gelu",
                    lambda v: jax.nn.gelu(v, approximate=approximate), (x,))


def swish(x, name=None):
    return silu(x)


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            from ...core import dtypes as _dt
            v = v.astype(_dt.convert_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)
    return apply_op("softmax", fn, (x,))


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            from ...core import dtypes as _dt
            v = v.astype(_dt.convert_dtype(dtype))
        return jax.nn.log_softmax(v, axis=axis)
    return apply_op("log_softmax", fn, (x,))


def softplus(x, beta=1, threshold=20, name=None):
    def fn(v):
        scaled = beta * v
        return jnp.where(scaled > threshold, v,
                         jnp.logaddexp(scaled, 0.0) / beta)
    return apply_op("softplus", fn, (x,))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda v: jnp.clip(v, min, max), (x,))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op("hardsigmoid",
                    lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), (x,))


def hardswish(x, name=None):
    return apply_op("hardswish", jax.nn.hard_swish, (x,))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu",
                    lambda v: jax.nn.leaky_relu(v, negative_slope), (x,))


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda v: jax.nn.elu(v, alpha), (x,))


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda v: jax.nn.celu(v, alpha), (x,))


def selu(x,
         scale=1.0507009873554805,
         alpha=1.6732632423543772, name=None):
    return apply_op(
        "selu",
        lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), (x,))


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)),
        (x,))


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(
        "hardshrink",
        lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), (x,))


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op("thresholded_relu",
                    lambda v: jnp.where(v > threshold, v, value), (x,))


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size > 1:
            shape = [1] * v.ndim
            ch_axis = 1 if data_format.startswith("NC") else v.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(v > 0, v, w * v)
    return apply_op("prelu", fn, (x, targ(weight)))


def rrelu(x, lower=0.125, upper=0.3333333, training=False, name=None):
    slope = (lower + upper) / 2.0
    return leaky_relu(x, slope)


def glu(x, axis=-1, name=None):
    def fn(v):
        a, b = jnp.split(v, 2, axis=axis)
        return a * jax.nn.sigmoid(b)
    return apply_op("glu", fn, (x,))


def swiglu(x, y=None, name=None):
    """Fused SwiGLU (parity: paddle.incubate.nn.functional.swiglu) — the
    Llama MLP gate; XLA fuses this into the surrounding matmuls."""
    if y is not None:
        return apply_op("swiglu", lambda a, b: jax.nn.silu(a) * b,
                        (x, targ(y)))
    def fn(v):
        a, b = jnp.split(v, 2, axis=-1)
        return jax.nn.silu(a) * b
    return apply_op("swiglu", fn, (x,))


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = (v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:])
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return apply_op("maxout", fn, (x,))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    """Parity: reference nn/functional/activation.py:1780 (gumbel_softmax;
    phi gumbel_softmax kernel).  Straight-through when ``hard``: the
    one-hot forward rides the soft sample's gradient."""
    from ...ops.random import next_key

    def fn(v, key):
        vf = v.astype(jnp.float32)
        g = jax.random.gumbel(key, v.shape, jnp.float32)
        soft = jax.nn.softmax((vf + g) / temperature, axis=axis)
        if hard:
            oh = jax.nn.one_hot(jnp.argmax(soft, axis=axis),
                                v.shape[axis], axis=axis,
                                dtype=soft.dtype)
            soft = jax.lax.stop_gradient(oh - soft) + soft
        return soft.astype(v.dtype)

    return apply_op("gumbel_softmax", fn, (x, next_key()))
