"""Vision-oriented functional ops: affine_grid, grid_sample,
channel_shuffle, temporal_shift.

Parity: python/paddle/nn/functional/vision.py (reference:
affine_grid:31, grid_sample:141, channel_shuffle:466,
extension.py temporal_shift:227).  Implemented as gather/reshape
compositions that XLA fuses; the 2^nd-corner interpolation keeps the
batched gathers large and static-shaped for the TPU backend.
"""
from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...ops._helpers import targ


def _grid_coords(n, align_corners, dtype):
    # normalized sample positions in [-1, 1] along one spatial dim
    if align_corners:
        return jnp.linspace(-1.0, 1.0, n, dtype=dtype)
    step = 2.0 / n
    return jnp.arange(n, dtype=dtype) * step + (step / 2 - 1.0)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a sampling grid from batched 2x3 (or 3x4) affine matrices.

    Parity: reference nn/functional/vision.py:31 (affine_grid).
    ``out_shape`` = [N, C, H, W] (or [N, C, D, H, W])."""
    if hasattr(out_shape, "numpy"):
        out_shape = [int(s) for s in out_shape.numpy().tolist()]
    out_shape = [int(s) for s in out_shape]

    def fn(th):
        dt = th.dtype
        if len(out_shape) == 4:
            n, _, h, w = out_shape
            ys = _grid_coords(h, align_corners, dt)
            xs = _grid_coords(w, align_corners, dt)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            base = jnp.stack(
                [gx, gy, jnp.ones_like(gx)], axis=-1)        # [H, W, 3]
            # [N, H, W, 2] = base @ theta^T
            return jnp.einsum("hwk,nak->nhwa", base, th)
        n, _, d, h, w = out_shape
        zs = _grid_coords(d, align_corners, dt)
        ys = _grid_coords(h, align_corners, dt)
        xs = _grid_coords(w, align_corners, dt)
        gz, gy, gx = jnp.meshgrid(zs, ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, gz, jnp.ones_like(gx)], axis=-1)
        return jnp.einsum("dhwk,nak->ndhwa", base, th)

    return apply_op("affine_grid", fn, (theta,))


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect(x, lo, hi):
    # reflect into [lo, hi] with period 2*(hi-lo)
    span = hi - lo
    x = jnp.abs(x - lo) % (2 * span)
    return lo + jnp.where(x > span, 2 * span - x, x)


def _resolve_coord(coord, size, padding_mode, align_corners):
    """Map normalized [-1,1] coords to pixel space under the padding mode.
    Returns (pixel_coord, in_bounds_mask_input)."""
    px = _unnormalize(coord, size, align_corners)
    if padding_mode == "reflection":
        if align_corners:
            px = _reflect(px, 0.0, float(size - 1)) if size > 1 \
                else jnp.zeros_like(px)
        else:
            px = _reflect(px, -0.5, size - 0.5)
            px = jnp.clip(px, 0, size - 1)
    elif padding_mode == "border":
        px = jnp.clip(px, 0, size - 1)
    return px


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample ``x`` at normalized ``grid`` locations (flow-field warp).

    Parity: reference nn/functional/vision.py:141 (grid_sample; phi
    grid_sample kernels).  4-D x [N,C,H,W] with grid [N,Ho,Wo,2] or 5-D
    x [N,C,D,H,W] with grid [N,Do,Ho,Wo,3]; grid's last dim orders
    coordinates fastest-varying-first (x=width, y=height, z=depth)."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported grid_sample mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")

    def fn(v, g):
        nd = v.ndim - 2                       # spatial rank (2 or 3)
        sizes = v.shape[2:]                   # (H, W) or (D, H, W)
        gf = g.astype(jnp.float32)
        # grid last-dim order is (x, y[, z]) = reversed spatial order
        coords = [gf[..., nd - 1 - i] for i in range(nd)]   # per spatial dim
        pix = [_resolve_coord(c, sizes[i], padding_mode, align_corners)
               for i, c in enumerate(coords)]

        def gather(idx_nd, valid):
            # idx_nd: list of [N, *out_sp] int arrays per spatial dim
            n = v.shape[0]
            bidx = jnp.arange(n).reshape((n,) + (1,) * (g.ndim - 2))
            bidx = jnp.broadcast_to(bidx, idx_nd[0].shape)
            clipped = [jnp.clip(ix, 0, sizes[i] - 1)
                       for i, ix in enumerate(idx_nd)]
            # v transposed to channel-last for a single batched gather
            vt = jnp.moveaxis(v, 1, -1)       # [N, *sp, C]
            out = vt[(bidx,) + tuple(clipped)]            # [N, *out_sp, C]
            if padding_mode == "zeros":
                out = out * valid[..., None].astype(out.dtype)
            return out

        if mode == "nearest":
            idx = [jnp.round(p).astype(jnp.int32) for p in pix]
            valid = jnp.ones(idx[0].shape, bool)
            if padding_mode == "zeros":
                for i, ix in enumerate(idx):
                    valid &= (ix >= 0) & (ix < sizes[i])
            out = gather(idx, valid)
        else:
            lo = [jnp.floor(p) for p in pix]
            out = 0.0
            for corner in itertools.product((0, 1), repeat=nd):
                idx = [(lo[i] + corner[i]).astype(jnp.int32)
                       for i in range(nd)]
                wgt = 1.0
                for i in range(nd):
                    frac = pix[i] - lo[i]
                    wgt = wgt * (frac if corner[i] else 1.0 - frac)
                valid = jnp.ones(idx[0].shape, bool)
                if padding_mode == "zeros":
                    for i, ix in enumerate(idx):
                        valid &= (ix >= 0) & (ix < sizes[i])
                out = out + gather(idx, valid) * wgt[..., None].astype(
                    jnp.float32)
        return jnp.moveaxis(out, -1, 1).astype(v.dtype)   # [N, C, *out_sp]

    return apply_op("grid_sample", fn, (x, targ(grid)))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """Parity: reference nn/functional/vision.py:466 (channel_shuffle)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unsupported data_format {data_format!r}")

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            return v.reshape(n, groups, c // groups, h, w) \
                    .swapaxes(1, 2).reshape(n, c, h, w)
        n, h, w, c = v.shape
        return v.reshape(n, h, w, groups, c // groups) \
                .swapaxes(3, 4).reshape(n, h, w, c)

    return apply_op("channel_shuffle", fn, (x,))


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """Shift a fraction of channels one step along the segment (time) axis.

    Parity: reference nn/functional/extension.py:227 (temporal_shift; phi
    temporal_shift kernel): the first ``C*ratio`` channels shift back
    (t-1), the next ``C*ratio`` shift forward (t+1), the rest stay."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unsupported data_format {data_format!r}")

    def fn(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        nt, c, h, w = v.shape
        n = nt // seg_num
        v5 = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        zeros = jnp.zeros_like(v5[:, :1])
        back = jnp.concatenate([v5[:, 1:], zeros], axis=1)[:, :, :c1]
        fwd = jnp.concatenate([zeros, v5[:, :-1]], axis=1)[:, :, c1:c2]
        out = jnp.concatenate([back, fwd, v5[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_op("temporal_shift", fn, (x,))
