"""Functional tail: distance/loss/decoding ops (round-4 surface sweep).

Parity: python/paddle/nn/functional/ (reference — distance.py
pairwise_distance, loss.py hsigmoid_loss/rnnt_loss/
triplet_margin_with_distance_loss, common.py class_center_sample,
pooling.py fractional_max_pool3d) and the generated inplace activation
variants (elu_/hardtanh_/...)."""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor
from ...core.dispatch import apply_op
from ...ops._helpers import targ


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False,
                      name=None):
    """Parity: paddle.nn.functional.pairwise_distance (distance.py)."""
    def fn(a, b):
        d = a - b + epsilon
        if p == float("inf"):
            out = jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim)
        elif p == 0:
            out = jnp.sum((d != 0).astype(a.dtype), axis=-1,
                          keepdims=keepdim)
        else:
            out = jnp.sum(jnp.abs(d) ** p, axis=-1,
                          keepdims=keepdim) ** (1.0 / p)
        return out
    return apply_op("pairwise_distance", fn, (x, targ(y)))


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Parity: loss.py triplet_margin_with_distance_loss."""
    dist = distance_function or (
        lambda a, b: pairwise_distance(a, b))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dsn = dist(positive, negative)
        dn = apply_op("minimum", jnp.minimum, (dn, targ(dsn)))

    def fn(a, b):
        loss = jnp.maximum(a - b + margin, 0.0)
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss
    return apply_op("triplet_margin_loss", fn, (dp, targ(dn)))


import functools


@functools.lru_cache(maxsize=32)
def _hsigmoid_tree(C: int):
    """Default complete-binary-tree path table — depends only on
    num_classes, so cache it (rebuilding the C*log2(C) table per forward
    would dominate large-vocab training steps)."""
    depth = max(1, int(math.ceil(math.log2(C))))
    table = np.zeros((C, depth), np.int32)
    code = np.zeros((C, depth), np.float32)
    valid = np.zeros((C, depth), np.float32)
    for c in range(C):
        # root-to-leaf walk of the complete binary tree: node ids are
        # the heap positions of c + C
        bits = bin(c + C)[3:]              # drop '0b1' (the root marker)
        node = 1
        for d, b in enumerate(bits):
            table[c, d] = node - 1         # internal node index
            code[c, d] = 1.0 if b == "1" else 0.0
            valid[c, d] = 1.0
            node = node * 2 + (1 if b == "1" else 0)
    return jnp.asarray(table), jnp.asarray(code), jnp.asarray(valid)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Parity: loss.py hsigmoid_loss — hierarchical sigmoid over the
    default complete binary tree (path for class c = bits of
    c + num_classes walked from the root), or a custom path_table/
    path_code pair.  weight: [num_classes-1, D] internal-node vectors."""
    C = int(num_classes)
    if path_table is None:
        table_j, code_j, valid_j = _hsigmoid_tree(C)
    else:
        table_j = path_table._value if isinstance(path_table, Tensor) \
            else jnp.asarray(path_table)
        code_j = (path_code._value if isinstance(path_code, Tensor)
                  else jnp.asarray(path_code)).astype(jnp.float32)
        valid_j = (table_j >= 0).astype(jnp.float32)
        table_j = jnp.maximum(table_j, 0)

    args = [input, label, targ(weight)] + ([bias] if bias is not None
                                           else [])

    def fn(x, lab, w, *b):
        lab = lab.reshape(-1).astype(jnp.int32)
        nodes = table_j[lab]                  # [B, depth]
        codes = code_j[lab]
        mask = valid_j[lab]
        wv = w[nodes]                         # [B, depth, D]
        logits = jnp.einsum("bd,bnd->bn", x.astype(jnp.float32),
                            wv.astype(jnp.float32))
        if b:
            logits = logits + b[0].reshape(-1)[nodes]
        # BCE-with-logits per node: code is the binary target
        per = jnp.maximum(logits, 0) - logits * codes \
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return (per * mask).sum(-1, keepdims=True)

    return apply_op("hsigmoid_loss", fn, tuple(args))


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """Parity: loss.py rnnt_loss — RNN-Transducer loss.

    input: [B, T, U+1, V] log-probs or logits (normalized internally);
    label: [B, U] int.  TPU-native: the alpha DP runs as a lax.scan over
    T (differentiable — reverse-mode AD through the scan yields the
    standard occupancy gradients, no hand-written backward kernel).
    FastEmit regularization is not implemented — a nonzero
    ``fastemit_lambda`` raises rather than being silently ignored."""
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: fastemit_lambda != 0 is not supported yet")
    def fn(logits, lab, in_len, lab_len):
        B, T, U1, V = logits.shape
        U = U1 - 1
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lab = lab.astype(jnp.int32)
        # per-(t,u) transition log-probs
        p_blank = logp[..., blank]                       # [B, T, U+1]
        lab_pad = jnp.pad(lab, ((0, 0), (0, 1)))          # [B, U+1]
        p_emit = jnp.take_along_axis(
            logp, lab_pad[:, None, :, None], axis=-1)[..., 0]

        NEG = -1e30
        u_idx = jnp.arange(U1)

        def row(alpha_prev, t):
            # alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
            #                         alpha[t, u-1] + emit(t, u-1))
            from_blank = alpha_prev + p_blank[:, t - 1, :]

            def inner(carry, u):
                # left-to-right within the row (sequential in u)
                prev_u = carry
                a = jnp.where(
                    u == 0, from_blank[:, 0],
                    jnp.logaddexp(
                        from_blank[:, u],
                        prev_u + p_emit[:, t, u - 1]))
                return a, a

            _, cols = lax.scan(inner, jnp.full((B,), NEG), u_idx)
            alpha_t = jnp.moveaxis(cols, 0, 1)           # [B, U+1]
            return alpha_t, None

        # t = 0 row: only emissions
        def first_row(carry, u):
            prev = carry
            a = jnp.where(u == 0, 0.0, prev + p_emit[:, 0, u - 1])
            return a, a

        _, cols0 = lax.scan(first_row, jnp.zeros((B,)), u_idx)
        alpha0 = jnp.moveaxis(cols0, 0, 1)

        def step(alpha, t):
            alpha_t, _ = row(alpha, t)
            return alpha_t, alpha_t

        _, rows = lax.scan(step, alpha0, jnp.arange(1, T))
        all_rows = jnp.concatenate([alpha0[None], rows], 0)  # [T, B, U+1]
        all_rows = jnp.moveaxis(all_rows, 1, 0)              # [B, T, U+1]

        bi = jnp.arange(B)
        t_last = (in_len - 1).astype(jnp.int32)
        u_last = lab_len.astype(jnp.int32)
        ll = all_rows[bi, t_last, u_last] \
            + p_blank[bi, t_last, u_last]
        loss = -ll
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply_op("rnnt_loss", fn,
                    (input, targ(label), targ(input_lengths),
                     targ(label_lengths)))


def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """Parity: common.py class_center_sample — sample num_samples class
    centers always including the labels' classes; returns
    (remapped_label, sampled_class_index)."""
    lab = label._value if isinstance(label, Tensor) else jnp.asarray(label)
    lab_np = np.asarray(lab).astype(np.int64)
    pos = np.unique(lab_np)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        # fresh negatives each call (reference samples per step), seeded
        # from the framework generator so paddle.seed reproduces the run
        from ...ops import random as _random
        key = np.asarray(jax.random.key_data(_random.next_key()))
        rng = np.random.default_rng(key.astype(np.uint32))
        extra = rng.choice(rest, num_samples - len(pos), replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(remap[lab_np]), Tensor(sampled.astype(np.int64)))


def fractional_max_pool3d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """Parity: pooling.py fractional_max_pool3d — pseudo-random pooling
    regions whose boundaries follow the fractional-stride sequence of
    Graham's fractional max-pooling paper."""
    xs = x.shape
    D, H, W = xs[-3], xs[-2], xs[-1]
    if isinstance(output_size, int):
        od = oh = ow = output_size
    else:
        od, oh, ow = output_size
    u = float(random_u) if random_u is not None else 0.5

    def edges(in_sz, out_sz):
        alpha = in_sz / out_sz
        # ceil(alpha * (i + u)) - ceil(alpha * u) boundary sequence
        idx = np.arange(out_sz + 1)
        e = np.ceil(alpha * (idx + u)).astype(np.int64) \
            - int(np.ceil(alpha * u))
        e = np.clip(e, 0, in_sz)
        e[-1] = in_sz
        return e

    ed, eh, ew = edges(D, od), edges(H, oh), edges(W, ow)

    def fn(v):
        outs, masks = [], []
        for i in range(od):
            for j in range(oh):
                for k in range(ow):
                    win = v[..., ed[i]:ed[i + 1], eh[j]:eh[j + 1],
                            ew[k]:ew[k + 1]]
                    outs.append(win.max((-3, -2, -1)))
                    if return_mask:
                        wd = ed[i + 1] - ed[i]
                        wh = eh[j + 1] - eh[j]
                        ww = ew[k + 1] - ew[k]
                        flat = win.reshape(win.shape[:-3] + (-1,))
                        am = flat.argmax(-1)
                        dz, rem = am // (wh * ww), am % (wh * ww)
                        dy, dx = rem // ww, rem % ww
                        gidx = ((ed[i] + dz) * H + eh[j] + dy) * W \
                            + ew[k] + dx
                        masks.append(gidx)
        out = jnp.stack(outs, -1).reshape(
            v.shape[:-3] + (od, oh, ow))
        if return_mask:
            m = jnp.stack(masks, -1).reshape(
                v.shape[:-3] + (od, oh, ow)).astype(jnp.int64)
            return out, m
        return out

    return apply_op("fractional_max_pool3d", fn, (x,))
