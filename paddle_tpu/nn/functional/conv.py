"""Convolutions.

Parity: python/paddle/nn/functional/conv.py (reference; phi conv kernels +
cuDNN).  TPU-native: a single lax.conv_general_dilated per call — XLA maps
it onto the MXU; layouts are handled by dimension_numbers instead of
NCHW/NHWC kernel variants.
"""
from __future__ import annotations

from typing import Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ...ops._helpers import targ


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v) if len(v) == n else tuple(v) * n
    return (int(v),) * n


def _padding(padding, nd, data_format):
    """Normalize paddle padding spec -> lax padding list of (lo, hi)."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    # full per-dim [[0,0],[0,0],[lo,hi],...] form
    if len(padding) == nd + 2:
        spatial = padding[2:] if data_format.startswith("NC") \
            else padding[1:-1]
        return [tuple(p) if isinstance(p, (list, tuple)) else (p, p)
                for p in spatial]
    raise ValueError(f"bad padding spec {padding}")


def _dim_numbers(nd, channel_last):
    if nd == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last \
            else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last \
        else ("NCDHW", "OIDHW", "NCDHW")


def _conv(name, nd, x, weight, bias, stride, padding, dilation, groups,
          data_format):
    channel_last = not data_format.startswith("NC")
    strides = _pair(stride, nd)
    dil = _pair(dilation, nd)
    pad = _padding(padding, nd, data_format)
    dn = _dim_numbers(nd, channel_last)

    def fn(v, w, *b):
        # paddle weights are [out, in/groups, *k] (OIHW); lax wants per dn.
        if channel_last:
            # OIHW -> HWIO
            w = jnp.moveaxis(w, (0, 1), (-1, -2))
        # NOTE: no preferred_element_type here — the TPU MXU already
        # accumulates bf16 convs in f32 internally, and requesting an
        # f32 output + downcast breaks jax's conv transpose rule under
        # value_and_grad (the f32 cotangent meets the bf16 weight)
        out = lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups)
        if b:
            bshape = [1] * out.ndim
            bshape[-1 if channel_last else 1] = b[0].size
            out = out + b[0].reshape(bshape)
        return out

    args = (x, targ(weight)) + ((targ(bias),) if bias is not None else ())
    return apply_op(name, fn, args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv("conv1d", 1, x, weight, bias, stride, padding, dilation,
                 groups, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv("conv2d", 2, x, weight, bias, stride, padding, dilation,
                 groups, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv("conv3d", 3, x, weight, bias, stride, padding, dilation,
                 groups, data_format)


def _conv_transpose(name, nd, x, weight, bias, stride, padding,
                    output_padding, dilation, groups, data_format,
                    output_size=None):
    channel_last = not data_format.startswith("NC")
    strides = _pair(stride, nd)
    dil = _pair(dilation, nd)
    pad = _padding(padding, nd, data_format)
    dn = _dim_numbers(nd, channel_last)
    opad = _pair(output_padding, nd)

    def fn(v, w, *b):
        # paddle transpose-conv weight: [in, out/groups, *k]
        if groups > 1:
            # grouped transposed conv via per-group slicing
            vin = jnp.split(v, groups, axis=-1 if channel_last else 1)
            win = jnp.split(w, groups, axis=0)
            outs = [
                _single_transpose(vv, ww) for vv, ww in zip(vin, win)]
            out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
        else:
            out = _single_transpose(v, w)
        if b:
            bshape = [1] * out.ndim
            bshape[-1 if channel_last else 1] = b[0].size
            out = out + b[0].reshape(bshape)
        return out

    def _single_transpose(v, w):
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            # convert conv padding to conv_transpose padding
            k = [(w.shape[2 + i] - 1) * dil[i] + 1 for i in range(nd)] \
                if not channel_last else \
                [(w.shape[i] - 1) * dil[i] + 1 for i in range(nd)]
            padding_cfg = [
                (k[i] - 1 - pad[i][0], k[i] - 1 - pad[i][1] + opad[i])
                for i in range(nd)]
        # IO(HW) -> lax transpose kernel layout
        if channel_last:
            wt = jnp.moveaxis(w, (0, 1), (-2, -1))  # I,O trailing
            kernel_spec = dn[1]
        else:
            wt = jnp.swapaxes(w, 0, 1)  # OI -> paddle in/out swap
            kernel_spec = dn[1]
        wt = jnp.flip(wt, axis=tuple(range(2, 2 + nd))) if not channel_last \
            else jnp.flip(wt, axis=tuple(range(nd)))
        return lax.conv_general_dilated(
            v, wt, window_strides=(1,) * nd, padding=padding_cfg,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn)

    args = (x, targ(weight)) + ((targ(bias),) if bias is not None else ())
    return apply_op(name, fn, args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose("conv1d_transpose", 1, x, weight, bias, stride,
                           padding, output_padding, dilation, groups, df)


def _resolve_output_padding(nd, x, weight, stride, padding, dilation,
                            output_size, output_padding, data_format):
    """Honor an explicit output_size by deriving the per-dim
    output_padding (parity: the reference's output_size handling);
    out = (in-1)*s - 2p + d*(k-1) + output_padding + 1."""
    if output_size is None:
        return output_padding

    def tup(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v,) * nd

    st, pd, dl = tup(stride), tup(padding), tup(dilation)
    xs = x.shape if hasattr(x, "shape") else x.shape
    spatial = list(xs[2:2 + nd]) if data_format.startswith("NC") \
        else list(xs[1:1 + nd])
    w = weight.shape
    ks = list(w[2:2 + nd])
    want = list(output_size)
    ops = []
    for i in range(nd):
        base = (spatial[i] - 1) * st[i] - 2 * pd[i] \
            + dl[i] * (ks[i] - 1) + 1
        op = int(want[i]) - base
        if not 0 <= op < max(st[i], dl[i]):
            raise ValueError(
                f"output_size[{i}]={want[i]} unreachable: base size "
                f"{base}, stride {st[i]}")
        ops.append(op)
    return tuple(ops)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    output_padding = _resolve_output_padding(
        2, x, weight, stride, padding, dilation, output_size,
        output_padding, data_format)
    return _conv_transpose("conv2d_transpose", 2, x, weight, bias, stride,
                           padding, output_padding, dilation, groups,
                           data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    output_padding = _resolve_output_padding(
        3, x, weight, stride, padding, dilation, output_size,
        output_padding, data_format)
    return _conv_transpose("conv3d_transpose", 3, x, weight, bias, stride,
                           padding, output_padding, dilation, groups,
                           data_format)
