"""Weight initializers.

Parity: python/paddle/nn/initializer/ (reference).  Each initializer is a
callable ``(shape, dtype) -> jax.Array`` using the global generator.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core import dtypes as _dt
from ..ops.random import next_key


def _fan_in_out(shape):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # paddle Linear weights are [in, out]; conv weights [out, in, kh, kw]
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    else:
        fan_out = shape[0] * receptive
        fan_in = shape[1] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, _dt.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = _dt.convert_dtype(dtype)
        return (jax.random.normal(next_key(), tuple(shape), jnp.float32)
                * self.std + self.mean).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        d = _dt.convert_dtype(dtype)
        out = jax.random.truncated_normal(next_key(), self.a, self.b,
                                          tuple(shape), jnp.float32)
        return (out * self.std + self.mean).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        d = _dt.convert_dtype(dtype)
        return jax.random.uniform(next_key(), tuple(shape), jnp.float32,
                                  self.low, self.high).astype(d)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ..core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), _dt.convert_dtype(dtype))
        return arr.reshape(tuple(shape))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            out[(i, i % ic) + mid] = 1.0
        return jnp.asarray(out, _dt.convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        a = np.asarray(jax.random.normal(next_key(), (max(rows, cols),
                                                      min(rows, cols))))
        q, r = np.linalg.qr(a)
        q = q * np.sign(np.diag(r))
        q = q.T if rows < cols else q
        return jnp.asarray(self.gain * q[:rows, :cols].reshape(shape),
                           _dt.convert_dtype(dtype))


# paddle aliases
constant_init = Constant
normal_init = Normal


# ---------------------------------------------------------------------------
# round-5 tail: Bilinear, set_global_initializer, calculate_gain, LazyGuard
# (parity: nn/initializer/__init__.py, initializer.py:118, lazy_init.py)
# ---------------------------------------------------------------------------
class Bilinear(Initializer):
    """Bilinear-interpolation kernel init for transposed-conv upsampling
    (parity: nn/initializer/Bilinear — the deconv upsampling recipe)."""

    def __call__(self, shape, dtype):
        import numpy as np
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D weight")
        # weight layout (C_out, C_in, H, W) like the reference
        h, w = shape[2], shape[3]
        f_h, f_w = (h + 1) // 2, (w + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = np.ogrid[:h, :w]
        filt = (1 - abs(og[0] / f_h - c_h)) * (1 - abs(og[1] / f_w - c_w))
        weight = np.zeros(shape, np.float32)
        rng = range(min(shape[0], shape[1]))
        for i in range(shape[0]):
            for j in range(shape[1]):
                if shape[0] == shape[1] and i != j:
                    continue
                weight[i, j] = filt
        import jax.numpy as jnp
        return jnp.asarray(weight, dtype)


_GLOBAL_INIT = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """Parity: nn/initializer/set_global_initializer — override the
    framework-default weight/bias initializers used by
    Layer.create_parameter when no explicit initializer is given.  Pass
    None to restore the defaults."""
    _GLOBAL_INIT["weight"] = weight_init
    _GLOBAL_INIT["bias"] = bias_init


def _global_initializer(is_bias: bool):
    return _GLOBAL_INIT["bias" if is_bias else "weight"]


def calculate_gain(nonlinearity, param=None):
    """Parity: nn/initializer/initializer.py:118 calculate_gain."""
    import math
    if param is None:
        param = 0.01
    recommended = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "conv_transpose1d": 1.0,
        "conv_transpose2d": 1.0, "conv_transpose3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + param ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in recommended:
        raise ValueError(
            f"nonlinearity {nonlinearity!r} has no recommended gain")
    return recommended[nonlinearity]


class LazyGuard:
    """Parity: nn/initializer/lazy_init.py LazyGuard — a scope in which
    Layer construction defers parameter materialization.  On this
    runtime parameters are jax arrays materialized lazily by XLA's
    async dispatch already, so the guard's observable contract (layers
    constructible before data/device placement; params valid after the
    scope) holds with immediate shapes."""

    def __enter__(self):
        _GLOBAL_INIT["_lazy"] = True
        return self

    def __exit__(self, *exc):
        _GLOBAL_INIT.pop("_lazy", None)
        return False
