"""paddle_tpu.nn — layers + functional.

Parity: python/paddle/nn/ (reference, SURVEY.md #62).
"""
from .layer_base import Layer, Parameter
from . import functional
from . import initializer
from .layers import (  # noqa: F401
    Linear, Embedding, Dropout, Dropout2D, Flatten, Identity, Upsample,
    PixelShuffle,
    Sequential, LayerList, ParameterList,
    Conv1D, Conv2D, Conv3D, Conv2DTranspose, Conv1DTranspose,
    Conv3DTranspose, SpectralNorm, FeatureAlphaDropout,
    AdaptiveLogSoftmaxWithLoss,
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, RMSNorm, GroupNorm, InstanceNorm2D, LocalResponseNorm,
    MaxPool1D, MaxPool2D, AvgPool1D, AvgPool2D, AdaptiveAvgPool2D,
    AdaptiveMaxPool2D,
    ReLU, ReLU6, GELU, SiLU, Swish, Sigmoid, Tanh, Softmax, LogSoftmax,
    Softplus, Softsign, LeakyReLU, ELU, CELU, SELU, Mish, Hardtanh,
    Hardsigmoid, Hardswish, Hardshrink, Softshrink, Tanhshrink, LogSigmoid,
    ThresholdedReLU, Maxout, GLU, PReLU,
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, CTCLoss, MarginRankingLoss,
    Pad2D, ZeroPad2D,
    Dropout3D, AlphaDropout, PixelUnshuffle, ChannelShuffle, MaxUnPool2D,
    FractionalMaxPool2D, Unfold, Fold, UpsamplingNearest2D,
    UpsamplingBilinear2D, Bilinear, CosineSimilarity, PairwiseDistance,
    SoftMarginLoss, MultiMarginLoss, MultiLabelSoftMarginLoss,
    PoissonNLLLoss, GaussianNLLLoss, TripletMarginLoss,
    AvgPool3D, MaxPool3D, AdaptiveAvgPool1D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool3D, MaxUnPool1D, MaxUnPool3D,
    Pad1D, Pad3D, InstanceNorm1D, InstanceNorm3D, CosineEmbeddingLoss,
    HingeEmbeddingLoss, TripletMarginWithDistanceLoss, LayerDict,
    Unflatten, Silu, Softmax2D, RReLU,
)
from .transformer import (MultiHeadAttention, TransformerEncoderLayer,
                          TransformerEncoder, TransformerDecoderLayer,
                          TransformerDecoder, Transformer)
from .rnn import (SimpleRNN, LSTM, GRU, SimpleRNNCell,
                  RNNCellBase, LSTMCell, GRUCell, RNN, BiRNN)
from .clip import ClipGradByNorm, ClipGradByValue, ClipGradByGlobalNorm

# round-4 tail
from .layers import (HSigmoidLoss, RNNTLoss, FractionalMaxPool3D)
from .decode import Decoder, BeamSearchDecoder, dynamic_decode
