"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Parity: python/paddle/nn/decode.py (reference — BeamSearchDecoder over
an RNNCell with batch*beam expansion, Decoder protocol
initialize/step/finalize, dynamic_decode loop).

TPU-native: the decode loop runs eagerly (each step is a compiled cell
call); beam bookkeeping (top-k over beam*vocab, state gather, finished
masking) is plain tensor math.  For a fully-compiled decode use
jit.to_static around a bounded loop instead.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Decoder protocol (parity: decode.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class BeamSearchDecoder(Decoder):
    """Parity: decode.py BeamSearchDecoder.

    cell: an RNNCell (``cell(inputs, states) -> (outputs, new_states)``);
    embedding_fn maps ids -> embeddings; output_fn maps cell outputs ->
    vocab logits."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers (parity: the tile_* static methods) -------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (repeat each batch row beam times)."""
        v = _v(x)
        out = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor._from_value(
            out.reshape((-1,) + tuple(v.shape[1:])))

    def _merge(self, v):
        return v.reshape((-1,) + tuple(v.shape[2:]))      # [B,K,...]→[BK,...]

    def _split(self, v):
        return v.reshape((-1, self.beam_size) + tuple(v.shape[1:]))

    def _map_states(self, states, fn):
        if isinstance(states, (list, tuple)):
            return type(states)(self._map_states(s, fn) for s in states)
        return Tensor._from_value(fn(_v(states)))

    # -- Decoder protocol ----------------------------------------------------
    def initialize(self, initial_cell_states):
        K = self.beam_size
        states = self._map_states(
            initial_cell_states,
            lambda v: self._merge(jnp.repeat(v[:, None], K, axis=1)))
        some = states[0] if isinstance(states, (list, tuple)) else states
        BK = _v(some).shape[0]
        B = BK // K
        ids = jnp.full((B, K), self.start_token, jnp.int64)
        # only beam 0 is live initially (others -inf so top-k picks
        # distinct continuations of the single start hypothesis)
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-1e9] * (K - 1)], jnp.float32), (B, 1))
        finished = jnp.zeros((B, K), bool)
        return (Tensor._from_value(ids), states,
                {"log_probs": log_probs, "finished": finished})

    def step(self, time, inputs, states, beam_state=None):
        K = self.beam_size
        ids = _v(inputs)                                 # [B, K]
        B = ids.shape[0]
        emb_in = Tensor._from_value(ids.reshape(-1))
        if self.embedding_fn is not None:
            emb = self.embedding_fn(emb_in)
        else:
            emb = emb_in
        cell_out, next_states = self.cell(emb, states)
        logits = self.output_fn(cell_out) if self.output_fn is not None \
            else cell_out
        logit_v = _v(logits).astype(jnp.float32)          # [BK, V]
        V = logit_v.shape[-1]
        step_lp = jax.nn.log_softmax(logit_v, axis=-1).reshape(B, K, V)

        prev_lp = beam_state["log_probs"]                 # [B, K]
        prev_fin = beam_state["finished"]
        # finished beams only extend with end_token at no cost
        end_only = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(prev_fin[..., None], end_only[None, None],
                            step_lp)
        total = prev_lp[..., None] + step_lp              # [B, K, V]
        flat = total.reshape(B, K * V)
        top_lp, top_idx = jax.lax.top_k(flat, K)
        beam_idx = top_idx // V                           # [B, K]
        token_idx = (top_idx % V).astype(jnp.int64)

        # gather states along the beam dim
        def gather(v):
            s = self._split(v)                            # [B, K, ...]
            out = jnp.take_along_axis(
                s, beam_idx.reshape((B, K) + (1,) * (s.ndim - 2)),
                axis=1)
            return self._merge(out)

        next_states = self._map_states(next_states, gather)
        finished = jnp.take_along_axis(prev_fin, beam_idx, axis=1) \
            | (token_idx == self.end_token)
        new_beam_state = {"log_probs": top_lp, "finished": finished}
        return (Tensor._from_value(token_idx),
                Tensor._from_value(beam_idx), next_states,
                new_beam_state)

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


def dynamic_decode(decoder: BeamSearchDecoder, inits=None,
                   max_step_num: int = 100, output_time_major=False,
                   impute_finished=False, is_test=False,
                   return_length=False, **kwargs):
    """Parity: decode.py dynamic_decode — run the decoder until every
    beam finished or max_step_num; returns (ids [B, K, T], beam
    log-probs) (+ lengths when return_length)."""
    inputs, states, beam = decoder.initialize(inits)
    B, K = _v(inputs).shape
    step_tokens = []
    step_parents = []
    lengths = jnp.zeros((B, K), jnp.int64)
    for t in range(int(max_step_num)):
        tokens, parents, states, beam = decoder.step(
            t, inputs, states, beam_state=beam)
        step_tokens.append(_v(tokens))
        step_parents.append(_v(parents))
        # lengths follow their hypotheses through the beam reorder
        lengths = jnp.take_along_axis(lengths, _v(parents), axis=1)
        lengths = jnp.where(beam["finished"] & (lengths == 0),
                            t + 1, lengths)
        inputs = tokens
        if bool(beam["finished"].all()):
            break
    lengths = jnp.where(lengths == 0, len(step_tokens), lengths)

    # backtrack parent pointers into full sequences (gather_tree)
    T = len(step_tokens)
    seq = np.zeros((B, K, T), np.int64)
    tok = [np.asarray(x) for x in step_tokens]
    par = [np.asarray(x) for x in step_parents]
    for b in range(B):
        for k in range(K):
            kk = k
            for t in range(T - 1, -1, -1):
                seq[b, k, t] = tok[t][b, kk]
                kk = int(par[t][b, kk])
    out_ids = Tensor._from_value(jnp.asarray(seq))
    scores = Tensor._from_value(beam["log_probs"])
    if return_length:
        return out_ids, scores, Tensor._from_value(lengths)
    return out_ids, scores
