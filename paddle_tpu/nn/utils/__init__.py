"""paddle.nn.utils (parity: python/paddle/nn/utils/__init__.py —
weight_norm/remove_weight_norm/spectral_norm reparametrizations via
forward pre-hooks, parameter<->vector packing, grad clipping)."""
from __future__ import annotations

from typing import List

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..layer_base import Layer, Parameter

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_except(w, dim):
    """L2 norm over all axes except ``dim`` (dim=None: full norm)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(w)))
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


class _WeightNormHook:
    def __init__(self, layer: Layer, name: str, dim):
        self.name = name
        self.dim = dim
        w = getattr(layer, name)
        g = Parameter(_norm_except(w._value, dim), name=f"{w.name}_g")
        v = Parameter(jnp.array(w._value), name=f"{w.name}_v")
        layer._parameters.pop(name, None)
        layer.add_parameter(name + "_g", g)
        layer.add_parameter(name + "_v", v)
        # the composed weight becomes a plain attribute refreshed by the
        # pre-hook so tape history flows g/v -> weight each forward
        self._compose(layer)

    def _compose(self, layer):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        norm = v / (Tensor._from_value(_norm_except(v._value, self.dim))
                    if v.stop_gradient else _norm_t(v, self.dim))
        w = g * norm
        object.__setattr__(layer, self.name, w)

    def __call__(self, layer, inputs):
        self._compose(layer)
        return None


def _norm_t(v: Tensor, dim):
    """Differentiable norm-except-dim on Tensors."""
    from ...core.dispatch import apply_op
    return apply_op("weight_norm_norm",
                    lambda x: _norm_except(x, dim), (v,))


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0):
    """Parity: nn.utils.weight_norm — reparametrize ``layer.name`` as
    g * v/||v|| with g/v trainable; recomposed every forward."""
    hook = _WeightNormHook(layer, name, dim)
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hook = (hook, handle)
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight"):
    """Parity: nn.utils.remove_weight_norm — bake the composed weight
    back into a single parameter."""
    hook, handle = layer._weight_norm_hook
    hook._compose(layer)
    w = getattr(layer, name)
    layer._parameters.pop(name + "_g", None)
    layer._parameters.pop(name + "_v", None)
    handle.remove()
    layer.add_parameter(name, Parameter(w._value))
    del layer._weight_norm_hook
    return layer


class _SpectralNormHook:
    def __init__(self, layer, name, n_power_iterations, eps, dim):
        self.name = name
        self.n = n_power_iterations
        self.eps = eps
        self.dim = dim
        w = getattr(layer, name)._value
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        key = jax.random.PRNGKey(0)
        self._u = jax.random.normal(key, (wm.shape[0],))
        self._u = self._u / (jnp.linalg.norm(self._u) + eps)

    def __call__(self, layer, inputs):
        from ...core.dispatch import apply_op
        w_p = layer._parameters.get(self.name + "_orig")
        w = w_p

        def fn(wv):
            wm = jnp.moveaxis(wv, self.dim, 0).reshape(wv.shape[self.dim],
                                                       -1)
            u = self._u
            for _ in range(self.n):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + self.eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + self.eps)
            sigma = u @ wm @ v
            return wv / sigma

        object.__setattr__(layer, self.name, apply_op(
            "spectral_norm_reparam", fn, (w,)))
        return None


def spectral_norm(layer: Layer, name: str = "weight",
                  n_power_iterations: int = 1, eps: float = 1e-12,
                  dim=None):
    """Parity: nn.utils.spectral_norm — divide the weight by its largest
    singular value (power iteration) each forward."""
    if dim is None:
        dim = 1 if layer.__class__.__name__ in (
            "Linear", "Embedding") else 0
    hook = _SpectralNormHook(layer, name, n_power_iterations, eps, dim)
    w = getattr(layer, name)
    layer._parameters.pop(name, None)
    layer.add_parameter(name + "_orig", w)
    hook(layer, None)
    layer.register_forward_pre_hook(hook)
    return layer


def parameters_to_vector(parameters, name=None) -> Tensor:
    """Parity: nn.utils.parameters_to_vector."""
    vals = [jnp.ravel(p._value) for p in parameters]
    return Tensor._from_value(jnp.concatenate(vals))


def vector_to_parameters(vec: Tensor, parameters, name=None):
    """Parity: nn.utils.vector_to_parameters (in-place set_value)."""
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p._value.shape)) if p._value.shape else 1
        p._value = v[off:off + n].reshape(p._value.shape) \
            .astype(p._value.dtype)
        off += n
    if off != v.shape[0]:
        raise ValueError(
            f"vector has {v.shape[0]} elements but parameters take {off}")


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Parity: nn.utils.clip_grad_norm_ — in-place global-norm clip of
    ``.grad``; returns the total norm."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p._grad for p in parameters
             if not p.stop_gradient and p._grad is not None]
    if not grads:
        return Tensor._from_value(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            "the total norm for gradients is non-finite")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if not p.stop_gradient and p._grad is not None:
            p._grad = (p._grad * scale).astype(p._grad.dtype)
    return Tensor._from_value(total)


def clip_grad_value_(parameters, clip_value):
    """Parity: nn.utils.clip_grad_value_ — elementwise grad clamp."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    cv = abs(float(clip_value))
    for p in parameters:
        if not p.stop_gradient and p._grad is not None:
            p._grad = jnp.clip(p._grad, -cv, cv)
