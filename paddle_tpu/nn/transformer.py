"""Transformer layers.

Parity: python/paddle/nn/layer/transformer.py (reference MultiHeadAttention,
TransformerEncoder/Decoder).  Attention uses the fused
scaled_dot_product_attention path (Pallas flash kernel on TPU).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from .layer_base import Layer
from .layers import Linear, Dropout, LayerNorm, LayerList, Sequential
from . import functional as F


class MultiHeadAttention(Layer):
    """Parity: paddle.nn.MultiHeadAttention (batch-first [B, S, D])."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self._cache = None

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = key if value is None else value
        B = query.shape[0]

        q = self.q_proj(query).reshape(
            [B, -1, self.num_heads, self.head_dim])
        k = self.k_proj(key).reshape([B, -1, self.num_heads, self.head_dim])
        v = self.v_proj(value).reshape(
            [B, -1, self.num_heads, self.head_dim])

        if cache is not None:
            from ..ops.manipulation import concat
            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            self._cache = (k, v)

        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.dropout if self.training else 0.0,
            training=self.training)
        out = out.reshape([B, -1, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out


def _get_activation(name):
    return {"relu": F.relu, "gelu": F.gelu}[name]


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = _get_activation(activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, src, src, src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(
            self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [encoder_layer if i == 0 else copy.deepcopy(encoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.norm3 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = _get_activation(activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(
            self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [decoder_layer if i == 0 else copy.deepcopy(decoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            self.encoder = TransformerEncoder(
                enc_layer, num_encoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            self.decoder = TransformerDecoder(
                dec_layer, num_decoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ..core.tensor import Tensor
        m = np.triu(np.full((length, length), -np.inf, np.float32), 1)
        return Tensor(m)
