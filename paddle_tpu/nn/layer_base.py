"""nn.Layer base class.

Parity: python/paddle/nn/layer/layers.py (reference Layer: parameter/buffer
registration, sublayers, hooks, state_dict, train/eval).  TPU-native
addition: ``functional_state`` / ``functional_call`` let a Layer be used as a
pure function over a params pytree — the seam jit/pjit tracing and the
distributed engine use to compile whole training steps into one XLA module.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtypes as _dt


class Parameter(Tensor):
    """Trainable tensor (parity: paddle EagerParamBase,
    python/paddle/base/framework.py)."""

    def __init__(self, data, trainable=True, name=None):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.is_distributed = False
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True


class Layer:
    """Base building block (parity: paddle.nn.Layer)."""

    _param_counter = 0

    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = _dt.convert_dtype(dtype)
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute routing ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                else:
                    params[name] = value
                return
            if layers is not None and name in layers:
                if value is None:
                    del layers[name]
                else:
                    layers[name] = value
                return
            buffers = self.__dict__.get("_buffers")
            if buffers is not None and name in buffers:
                buffers[name] = value
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- registration --------------------------------------------------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, dtype=None, default_initializer=None,
                         attr=None, is_bias=False) -> Parameter:
        """Parity: Layer.create_parameter — initializer-driven creation."""
        from . import initializer as I
        dtype = _dt.convert_dtype(dtype) if dtype else self._dtype
        init = None
        name = None
        if attr is not None and attr is not False:
            init = getattr(attr, "initializer", None)
            name = getattr(attr, "name", None)
        if init is None:
            # a user ParamAttr initializer wins; otherwise the global
            # override (set_global_initializer) beats the layer's own
            # default, matching reference precedence
            init = I._global_initializer(is_bias)
        if init is None:
            init = default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        value = init(shape, dtype)
        if name is None:
            # paddle-style structured names (linear_0.w_0) so
            # apply_decay_param_fun / exclude_from_weight_decay_fn
            # conventions keyed on ".b_"/".w_" work
            Layer._param_counter += 1
            name = (f"{self._name_scope}_{Layer._param_counter}."
                    f"{'b' if is_bias else 'w'}_0")
        p = Parameter(value, name=name)
        return p

    # -- traversal -----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix, True)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix, False)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items()
                    if l is not None)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- mode ----------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # -- hooks ---------------------------------------------------------------
    class _HookHandle:
        def __init__(self, store, hid):
            self._store, self._hid = store, hid

        def remove(self):
            self._store.pop(self._hid, None)

    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return Layer._HookHandle(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return Layer._HookHandle(self._forward_post_hooks, self._hook_id)

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers():
            last = name.rsplit(".", 1)[-1]
            if last not in self._non_persistable_buffer_names:
                dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Parity: Layer.set_state_dict / set_dict."""
        missing, unexpected = [], []
        own = dict(self.named_parameters())
        own.update(dict(self.named_buffers()))
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                val = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                tgt.set_value(val.astype(tgt.numpy().dtype)
                              if val.dtype != np.asarray(tgt._value).dtype
                              else val)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / conversion --------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = _dt.convert_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(d)
            for b in self.buffers():
                if b is not None and jnp.issubdtype(b._value.dtype,
                                                    jnp.floating):
                    b._value = b._value.astype(d)
            self._dtype = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- functionalization (the jit/pjit seam) -------------------------------
    def functional_state(self) -> Dict[str, jax.Array]:
        """Raw param+buffer values keyed by structured name."""
        return {k: v._value for k, v in self.state_dict().items()}

    @contextlib.contextmanager
    def bind_state(self, state: Dict[str, Any]):
        """Temporarily swap parameter/buffer values (possibly tracers) —
        functional_call support for tracing whole steps under jax.jit."""
        sd = self.state_dict()
        old = {k: sd[k]._value for k in state if k in sd}
        try:
            for k, v in state.items():
                if k in sd:
                    sd[k]._value = v
            yield self
        finally:
            for k, v in old.items():
                sd[k]._value = v

    def functional_call(self, state: Dict[str, Any], *args, **kwargs):
        with self.bind_state(state):
            return self(*args, **kwargs)

    # -- misc ----------------------------------------------------------------
    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        lines = [self.__class__.__name__ + "("]
        extra = self.extra_repr()
        if extra:
            lines[0] += extra
        for name, layer in self._sub_layers.items():
            rep = repr(layer).replace("\n", "\n  ")
            lines.append(f"  ({name}): {rep}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else lines[0] + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
