"""Detection op family.

Parity: the reference's detection kernels —
paddle/phi/kernels/impl/box_coder.h, prior_box_kernel.cc,
yolo_box_kernel.cc, yolo_loss (phi/kernels/impl/yolo_loss...),
matrix_nms_kernel.cc, multiclass_nms3, generate_proposals_v2,
distribute_fpn_proposals, psroi_pool, deformable_conv.

TPU-native: everything is expressed as dense vectorized jnp over fixed
shapes (sorting + masks instead of data-dependent loops), so the whole
family traces under jit; NMS-style selection returns fixed-size outputs
with a valid-count, the XLA-friendly shape discipline.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.dispatch import apply_op
from ..ops._helpers import as_value, wrap, targ


# ---------------------------------------------------------------------------
# box_coder
# ---------------------------------------------------------------------------
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, variance=None, name=None):
    """Parity: reference box_coder op (encode/decode center-size)."""
    def fn(pb, tb, *rest):
        pbv = rest[0] if rest else None
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            # [N_target, N_prior, 4]
            dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            dw = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            dh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([dx, dy, dw, dh], axis=-1)
            if pbv is not None:
                out = out / pbv[None, :, :]
            elif variance:
                out = out / jnp.asarray(variance)[None, None, :]
            return out
        # decode_center_size: tb is [N, M, 4] deltas (or [N,4] with
        # priors broadcast on `axis`)
        deltas = tb if tb.ndim == 3 else tb[:, None, :]
        if pbv is not None:
            deltas = deltas * pbv[None, :, :]
        elif variance:
            deltas = deltas * jnp.asarray(variance)[None, None, :]
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[None, :], ph[None, :],
                                    pcx[None, :], pcy[None, :])
        else:
            pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None],
                                    pcx[:, None], pcy[:, None])
        cx = deltas[..., 0] * pw_ + pcx_
        cy = deltas[..., 1] * ph_ + pcy_
        w = jnp.exp(deltas[..., 2]) * pw_
        h = jnp.exp(deltas[..., 3]) * ph_
        out = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                         cx + w * 0.5 - norm, cy + h * 0.5 - norm],
                        axis=-1)
        return out if tb.ndim == 3 else out[:, 0, :]
    args = (prior_box, targ(target_box))
    if prior_box_var is not None:
        args = args + (targ(prior_box_var),)
    return apply_op("box_coder", fn, args)


# ---------------------------------------------------------------------------
# prior_box (SSD)
# ---------------------------------------------------------------------------
def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variances=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """Parity: reference prior_box op (SSD prior/anchor generation)."""
    fh, fw = as_value(input).shape[-2:]
    ih, iw = as_value(image).shape[-2:]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    whs = []
    for k, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                bs = np.sqrt(ms * max_sizes[k])
                whs.append((bs, bs))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                bs = np.sqrt(ms * max_sizes[k])
                whs.append((bs, bs))
    whs = np.asarray(whs, np.float32)            # [P, 2]

    cx = (np.arange(fw, dtype=np.float32) + offset) * step_w
    cy = (np.arange(fh, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)               # [fh, fw]
    boxes = np.stack([
        (cxg[..., None] - whs[None, None, :, 0] / 2) / iw,
        (cyg[..., None] - whs[None, None, :, 1] / 2) / ih,
        (cxg[..., None] + whs[None, None, :, 0] / 2) / iw,
        (cyg[..., None] + whs[None, None, :, 1] / 2) / ih,
    ], axis=-1)                                   # [fh, fw, P, 4]
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          boxes.shape).copy()
    return wrap(jnp.asarray(boxes)), wrap(jnp.asarray(var))


# ---------------------------------------------------------------------------
# yolo_box / yolo_loss
# ---------------------------------------------------------------------------
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Parity: reference yolo_box op (decode a YOLOv3 head)."""
    def fn(xv, imgs):
        n, c, h, w = xv.shape
        an = np.asarray(anchors, np.float32).reshape(-1, 2)
        na = an.shape[0]
        xv = xv.reshape(n, na, -1, h, w)          # [N, A, 5+C(+1), H, W]
        if iou_aware:
            ioup = jax.nn.sigmoid(xv[:, :, -1])
            xv = xv[:, :, :-1]
        gx = (jnp.arange(w, dtype=jnp.float32))[None, None, None, :]
        gy = (jnp.arange(h, dtype=jnp.float32))[None, None, :, None]
        bx = (gx + jax.nn.sigmoid(xv[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2) / w
        by = (gy + jax.nn.sigmoid(xv[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2) / h
        input_h = downsample_ratio * h
        input_w = downsample_ratio * w
        bw = jnp.exp(xv[:, :, 2]) * an[None, :, 0, None, None] / input_w
        bh = jnp.exp(xv[:, :, 3]) * an[None, :, 1, None, None] / input_h
        conf = jax.nn.sigmoid(xv[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * \
                ioup ** iou_aware_factor
        prob = jax.nn.sigmoid(xv[:, :, 5:]) * conf[:, :, None]
        keep = conf > conf_thresh
        imh = imgs[:, 0].astype(jnp.float32)
        imw = imgs[:, 1].astype(jnp.float32)
        x0 = (bx - bw / 2) * imw[:, None, None, None]
        y0 = (by - bh / 2) * imh[:, None, None, None]
        x1 = (bx + bw / 2) * imw[:, None, None, None]
        y1 = (by + bh / 2) * imh[:, None, None, None]
        if clip_bbox:
            x0 = jnp.clip(x0, 0)
            y0 = jnp.clip(y0, 0)
            x1 = jnp.minimum(x1, imw[:, None, None, None] - 1)
            y1 = jnp.minimum(y1, imh[:, None, None, None] - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1)
        boxes = boxes * keep[..., None]
        boxes = boxes.reshape(n, -1, 4)
        scores = (prob * keep[:, :, None]).transpose(0, 1, 3, 4, 2)
        scores = scores.reshape(n, -1, class_num)
        return boxes, scores
    return apply_op("yolo_box", fn, (x, targ(img_size)))


def yolo_loss(x, gt_box, gt_label, gt_score=None, anchors=(),
              anchor_mask=(), class_num=0, ignore_thresh=0.7,
              downsample_ratio=32, use_label_smooth=True,
              scale_x_y=1.0, name=None):
    """Parity: reference yolo_loss op (YOLOv3 training loss: xywh
    regression + objectness/class BCE with ignore-region masking)."""
    def fn(xv, gb, gl, *rest):
        gs = rest[0] if rest else None
        n, c, h, w = xv.shape
        an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
        mask = np.asarray(anchor_mask, np.int64)
        an = an_all[mask]
        na = an.shape[0]
        xv = xv.reshape(n, na, 5 + class_num, h, w)
        input_size = downsample_ratio * h
        b = gb.shape[1]

        # predicted boxes (normalized)
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        px = (gx + jax.nn.sigmoid(xv[:, :, 0])) / w
        py = (gy + jax.nn.sigmoid(xv[:, :, 1])) / h
        pw = jnp.exp(xv[:, :, 2]) * an[None, :, 0, None, None] \
            / input_size
        ph = jnp.exp(xv[:, :, 3]) * an[None, :, 1, None, None] \
            / input_size

        # iou of every predicted box with every gt -> ignore mask
        pb = jnp.stack([px - pw / 2, py - ph / 2, px + pw / 2,
                        py + ph / 2], -1)          # [N,A,H,W,4]
        gbx = jnp.stack([gb[..., 0] - gb[..., 2] / 2,
                         gb[..., 1] - gb[..., 3] / 2,
                         gb[..., 0] + gb[..., 2] / 2,
                         gb[..., 1] + gb[..., 3] / 2], -1)  # [N,B,4]
        lt = jnp.maximum(pb[..., None, :2], gbx[:, None, None, None, :, :2])
        rb = jnp.minimum(pb[..., None, 2:], gbx[:, None, None, None, :, 2:])
        whi = jnp.clip(rb - lt, 0)
        inter = whi[..., 0] * whi[..., 1]
        area_p = pw * ph
        area_g = (gb[..., 2] * gb[..., 3])[:, None, None, None, :]
        iou = inter / jnp.maximum(area_p[..., None] + area_g - inter,
                                  1e-10)
        best_iou = jnp.max(iou, axis=-1)
        ignore = best_iou > ignore_thresh

        # gt -> (anchor, cell) assignment: best wh-iou over ALL anchors,
        # responsibility only when the argmax falls in this head's mask
        gw = gb[..., 2] * input_size
        gh = gb[..., 3] * input_size
        inter_wh = jnp.minimum(gw[..., None], an_all[None, None, :, 0]) * \
            jnp.minimum(gh[..., None], an_all[None, None, :, 1])
        union_wh = gw[..., None] * gh[..., None] + \
            (an_all[:, 0] * an_all[:, 1])[None, None, :] - inter_wh
        an_iou = inter_wh / jnp.maximum(union_wh, 1e-10)
        best_an = jnp.argmax(an_iou, axis=-1)     # [N, B]
        valid = (gb[..., 2] > 0) & (gb[..., 3] > 0)

        gi = jnp.clip((gb[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gb[..., 1] * h).astype(jnp.int32), 0, h - 1)

        loss = jnp.zeros((n,), jnp.float32)
        obj_target = jnp.zeros((n, na, h, w), jnp.float32)
        # scatter per-gt losses (vectorized over batch x gt)
        for a_idx in range(na):
            resp = valid & (best_an == mask[a_idx])
            tx = gb[..., 0] * w - gi
            ty = gb[..., 1] * h - gj
            tw = jnp.log(jnp.maximum(
                gw / an[a_idx, 0], 1e-9))
            th = jnp.log(jnp.maximum(
                gh / an[a_idx, 1], 1e-9))
            scale = 2.0 - gb[..., 2] * gb[..., 3]
            # gather predictions at each gt's responsible cell (gj, gi)
            px_ = xv[jnp.arange(n)[:, None], a_idx, 0, gj, gi]
            py_ = xv[jnp.arange(n)[:, None], a_idx, 1, gj, gi]
            pw_ = xv[jnp.arange(n)[:, None], a_idx, 2, gj, gi]
            ph_ = xv[jnp.arange(n)[:, None], a_idx, 3, gj, gi]
            w_resp = resp.astype(jnp.float32) * scale
            bce = lambda lg, tgt: jnp.maximum(lg, 0) - lg * tgt + \
                jnp.log1p(jnp.exp(-jnp.abs(lg)))
            lx = bce(px_, tx) + bce(py_, ty)
            lwh = (pw_ - tw) ** 2 + (ph_ - th) ** 2
            loss = loss + jnp.sum(w_resp * (lx + 0.5 * lwh), axis=1)
            # class loss
            smooth = 1.0 / class_num if use_label_smooth else 0.0
            cls_logit = jnp.moveaxis(
                xv[:, a_idx, 5:], 1, -1)[
                    jnp.arange(n)[:, None], gj, gi]       # [N,B,C]
            tgt_cls = jax.nn.one_hot(gl, class_num) * (1 - smooth * 2) \
                + smooth
            lcls = jnp.sum(bce(cls_logit, tgt_cls), axis=-1)
            if gs is not None:
                lcls = lcls * gs
            loss = loss + jnp.sum(resp.astype(jnp.float32) * lcls,
                                  axis=1)
            # objectness target scatter
            obj_target = obj_target.at[
                jnp.arange(n)[:, None], a_idx, gj, gi].max(
                    resp.astype(jnp.float32))
        # objectness loss: positives get BCE target 1; ignored cells drop
        obj_logit = xv[:, :, 4]
        bce = lambda lg, tgt: jnp.maximum(lg, 0) - lg * tgt + \
            jnp.log1p(jnp.exp(-jnp.abs(lg)))
        lobj = bce(obj_logit, obj_target)
        noobj_mask = (obj_target == 0) & (~ignore)
        loss = loss + jnp.sum(
            lobj * (obj_target + noobj_mask.astype(jnp.float32)),
            axis=(1, 2, 3))
        return loss
    args = (x, targ(gt_box), targ(gt_label))
    if gt_score is not None:
        args = args + (targ(gt_score),)
    return apply_op("yolo_loss", fn, args)


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------
def _iou_matrix(boxes):
    area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                               1e-10)


def matrix_nms(bboxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0,
               normalized=True, name=None):
    """Parity: reference matrix_nms op (SOLOv2 decay-based NMS) —
    fully vectorized: score decay via the pairwise IoU matrix, no
    sequential suppression loop."""
    def fn(bx, sc):
        # single image: bx [M, 4]; sc [C, M]
        bxv = bx[0] if bx.ndim == 3 else bx
        scv = sc[0] if sc.ndim == 3 else sc
        C, M = scv.shape
        outs = []
        for c in range(C):
            if c == background_label:
                continue
            s = scv[c]
            valid = s > score_threshold
            s = jnp.where(valid, s, 0.0)
            k = min(nms_top_k if nms_top_k > 0 else M, M)
            top_s, top_i = lax.top_k(s, k)
            b = bxv[top_i]
            iou = jnp.triu(_iou_matrix(b), 1)     # [i, j]: i higher-scored
            # SOLOv2 matrix NMS: decay_j = min_i f(iou_ij) / f(cmax_i)
            # where cmax_i is suppressor i's own max overlap with ITS
            # higher-scored boxes
            cmax = jnp.max(iou, axis=0)           # [k]
            tri = jnp.triu(jnp.ones_like(iou), 1) > 0
            if use_gaussian:
                decay = jnp.exp(-(iou ** 2 - cmax[:, None] ** 2)
                                / gaussian_sigma)
            else:
                decay = (1 - iou) / jnp.maximum(1 - cmax[:, None],
                                                1e-10)
            decay = jnp.min(jnp.where(tri, decay, 1.0), axis=0)
            dec_s = top_s * decay
            keep = dec_s > post_threshold
            cls = jnp.full((k, 1), c, jnp.float32)
            outs.append(jnp.concatenate(
                [cls, (dec_s * keep)[:, None], b], axis=1))
        if not outs:
            return jnp.zeros((0, 6), jnp.float32), \
                jnp.zeros((1,), jnp.int32)
        cat = jnp.concatenate(outs, axis=0)
        kk = min(keep_top_k if keep_top_k > 0 else cat.shape[0],
                 cat.shape[0])
        top_s2, top_i2 = lax.top_k(cat[:, 1], kk)
        sel = cat[top_i2]
        count = jnp.sum((sel[:, 1] > 0).astype(jnp.int32))
        return sel, count.reshape(1)
    return apply_op("matrix_nms", fn, (bboxes, targ(scores)))


def multiclass_nms3(bboxes, scores, rois_num=None, score_threshold=0.05,
                    nms_top_k=400, keep_top_k=200, nms_threshold=0.5,
                    normalized=True, nms_eta=1.0, background_label=0,
                    name=None):
    """Parity: reference multiclass_nms3 op — per-class greedy NMS +
    global keep_top_k, fixed-size outputs with valid count."""
    def fn(bx, sc):
        bxv = bx[0] if bx.ndim == 3 else bx
        scv = sc[0] if sc.ndim == 3 else sc
        C, M = scv.shape
        outs, orig_idx = [], []
        for c in range(C):
            if c == background_label:
                continue
            s = scv[c]
            k = min(nms_top_k if nms_top_k > 0 else M, M)
            top_s, top_i = lax.top_k(jnp.where(s > score_threshold, s,
                                               0.0), k)
            b = bxv[top_i]
            iou = _iou_matrix(b)

            def body(i, keep):
                sup = (iou[i] > nms_threshold) & keep[i] & \
                    (jnp.arange(k) > i)
                return keep & (~sup)

            keep = lax.fori_loop(0, k, body,
                                 jnp.ones((k,), bool)) & (top_s > 0)
            cls = jnp.full((k, 1), c, jnp.float32)
            outs.append(jnp.concatenate(
                [cls, (top_s * keep)[:, None], b], axis=1))
            orig_idx.append(top_i)                 # original box rows
        cat = jnp.concatenate(outs, axis=0)
        cat_idx = jnp.concatenate(orig_idx, axis=0)
        kk = min(keep_top_k if keep_top_k > 0 else cat.shape[0],
                 cat.shape[0])
        top_s2, top_i2 = lax.top_k(cat[:, 1], kk)
        sel = cat[top_i2]
        count = jnp.sum((sel[:, 1] > 0).astype(jnp.int32))
        index = cat_idx[top_i2]                    # original box ids
        return sel, index.astype(jnp.int32), count.reshape(1)
    return apply_op("multiclass_nms3", fn, (bboxes, targ(scores)))


# ---------------------------------------------------------------------------
# proposals
# ---------------------------------------------------------------------------
def generate_proposals(scores, bbox_deltas, im_shape, anchors,
                       variances=None, pre_nms_top_n=6000,
                       post_nms_top_n=1000, nms_thresh=0.5, min_size=0.1,
                       eta=1.0, pixel_offset=False, name=None):
    """Parity: reference generate_proposals_v2 op (RPN head)."""
    def fn(sc, bd, ims, an, *rest):
        var = rest[0] if rest else None
        n = sc.shape[0]
        A = an.reshape(-1, 4).shape[0]
        anf = an.reshape(-1, 4)
        s = sc.reshape(n, -1)                     # [N, A*H*W]
        d = bd.reshape(n, -1, 4)
        if var is not None:
            d = d * var.reshape(-1, 4)[None]
        off = 1.0 if pixel_offset else 0.0
        aw = anf[:, 2] - anf[:, 0] + off
        ah = anf[:, 3] - anf[:, 1] + off
        acx = anf[:, 0] + aw * 0.5
        acy = anf[:, 1] + ah * 0.5
        cx = d[..., 0] * aw + acx
        cy = d[..., 1] * ah + acy
        w = jnp.exp(jnp.clip(d[..., 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(d[..., 3], -10, 10)) * ah
        boxes = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                           cx + w * 0.5 - off, cy + h * 0.5 - off], -1)
        imh = ims[:, 0][:, None]
        imw = ims[:, 1][:, None]
        boxes = jnp.stack([
            jnp.clip(boxes[..., 0], 0, imw - 1),
            jnp.clip(boxes[..., 1], 0, imh - 1),
            jnp.clip(boxes[..., 2], 0, imw - 1),
            jnp.clip(boxes[..., 3], 0, imh - 1)], -1)
        bw = boxes[..., 2] - boxes[..., 0] + off
        bh = boxes[..., 3] - boxes[..., 1] + off
        ok = (bw >= min_size) & (bh >= min_size)
        s = jnp.where(ok, s, -1.0)
        k = min(pre_nms_top_n, s.shape[1])
        top_s, top_i = lax.top_k(s, k)
        bsel = jnp.take_along_axis(boxes, top_i[..., None], axis=1)
        # per-image greedy NMS
        outs_b, outs_s, counts = [], [], []
        for b_i in range(n):
            iou = _iou_matrix(bsel[b_i])

            def body(i, keep):
                sup = (iou[i] > nms_thresh) & keep[i] & \
                    (jnp.arange(k) > i)
                return keep & (~sup)

            keep = lax.fori_loop(0, k, body, jnp.ones((k,), bool))
            keep = keep & (top_s[b_i] > 0)
            sc_k = jnp.where(keep, top_s[b_i], -1.0)
            kk = min(post_nms_top_n, k)
            fs, fi = lax.top_k(sc_k, kk)
            outs_b.append(bsel[b_i][fi])
            outs_s.append(jnp.maximum(fs, 0))
            counts.append(jnp.sum((fs > 0).astype(jnp.int32)))
        return (jnp.stack(outs_b).reshape(-1, 4),
                jnp.stack(outs_s).reshape(-1),
                jnp.stack(counts))
    args = (scores, targ(bbox_deltas), targ(im_shape), targ(anchors))
    if variances is not None:
        args = args + (targ(variances),)
    return apply_op("generate_proposals", fn, args)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Parity: reference distribute_fpn_proposals op — assign each RoI
    to an FPN level by sqrt-area scale."""
    def fn(rois):
        off = 1.0 if pixel_offset else 0.0
        w = rois[:, 2] - rois[:, 0] + off
        h = rois[:, 3] - rois[:, 1] + off
        scale = jnp.sqrt(jnp.maximum(w * h, 1e-10))
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + \
            refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        outs, counts = [], []
        for L in range(min_level, max_level + 1):
            m = lvl == L
            idx = jnp.argsort(~m, stable=True)    # level-L rois first
            cnt = jnp.sum(m.astype(jnp.int32))
            sel = rois[idx]
            sel = jnp.where((jnp.arange(rois.shape[0]) < cnt)[:, None],
                            sel, 0.0)
            outs.append(sel)
            counts.append(cnt)
        # restore index: position of each original roi in the
        # level-sorted concatenation (inverse of the stable level sort)
        order = jnp.argsort(lvl, stable=True)
        restore = jnp.argsort(order, stable=True).astype(jnp.int32)
        return tuple(outs) + (restore[:, None], jnp.stack(counts))
    return apply_op("distribute_fpn_proposals", fn, (fpn_rois,))


def psroi_pool(x, boxes, boxes_num=None, output_size=7,
               spatial_scale=1.0, output_channels=None, name=None):
    """Parity: reference psroi_pool op (position-sensitive RoI AVERAGE
    pooling: output channel c at bin (i,j) averages input channel
    c*k*k + i*k + j over the bin's integer pixel window).  Exact bin
    means via a 2-D integral image (one cumsum, then 4 gathers)."""
    def fn(xv, bx, *rest):
        N, C, H, W = xv.shape
        k = output_size if isinstance(output_size, int) \
            else output_size[0]
        oc = output_channels or C // (k * k)
        M = bx.shape[0]
        if rest:
            bnum = rest[0].reshape(-1).astype(jnp.int32)
            bid = jnp.repeat(jnp.arange(N), bnum,
                             total_repeat_length=M)
        else:
            bid = jnp.zeros((M,), jnp.int32)
        x0 = jnp.round(bx[:, 0] * spatial_scale)
        y0 = jnp.round(bx[:, 1] * spatial_scale)
        x1 = jnp.round(bx[:, 2] * spatial_scale)
        y1 = jnp.round(bx[:, 3] * spatial_scale)
        bw = jnp.maximum(x1 - x0, 0.1) / k
        bh = jnp.maximum(y1 - y0, 0.1) / k
        ii = jnp.arange(k, dtype=jnp.float32)
        # integer bin edges, floor start / ceil end (reference kernel)
        ys = jnp.clip(jnp.floor(y0[:, None] + ii[None] * bh[:, None])
                      .astype(jnp.int32), 0, H)          # [M, k]
        ye = jnp.clip(jnp.ceil(y0[:, None] + (ii[None] + 1)
                               * bh[:, None]).astype(jnp.int32), 0, H)
        xs = jnp.clip(jnp.floor(x0[:, None] + ii[None] * bw[:, None])
                      .astype(jnp.int32), 0, W)
        xe = jnp.clip(jnp.ceil(x0[:, None] + (ii[None] + 1)
                               * bw[:, None]).astype(jnp.int32), 0, W)
        # integral image with a zero top/left border: [N, C, H+1, W+1]
        sat = jnp.pad(jnp.cumsum(jnp.cumsum(
            xv.astype(jnp.float32), axis=2), axis=3),
            ((0, 0), (0, 0), (1, 0), (1, 0)))
        cidx = (jnp.arange(oc)[:, None, None] * k * k
                + jnp.arange(k)[None, :, None] * k
                + jnp.arange(k)[None, None, :])          # [oc, k, k]
        cb = jnp.broadcast_to(cidx[None], (M, oc, k, k))
        bidb = jnp.broadcast_to(bid[:, None, None, None],
                                (M, oc, k, k))
        y0b = jnp.broadcast_to(ys[:, None, :, None], (M, oc, k, k))
        y1b = jnp.broadcast_to(ye[:, None, :, None], (M, oc, k, k))
        x0b = jnp.broadcast_to(xs[:, None, None, :], (M, oc, k, k))
        x1b = jnp.broadcast_to(xe[:, None, None, :], (M, oc, k, k))
        bin_sum = (sat[bidb, cb, y1b, x1b] - sat[bidb, cb, y0b, x1b]
                   - sat[bidb, cb, y1b, x0b] + sat[bidb, cb, y0b, x0b])
        area = jnp.maximum((y1b - y0b) * (x1b - x0b), 1)
        return (bin_sum / area).astype(xv.dtype)
    args = (x, targ(boxes))
    if boxes_num is not None:
        args = args + (targ(boxes_num),)
    return apply_op("psroi_pool", fn, args)


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------
def deformable_conv(x, offset, weight, mask=None, stride=1, padding=0,
                    dilation=1, deformable_groups=1, groups=1,
                    im2col_step=64, name=None):
    """Parity: reference deformable_conv op (v1/v2 with mask) —
    bilinear-sample the kernel taps at offset positions (dense gather,
    MXU matmul for the channel contraction)."""
    def fn(xv, off, wv, *rest):
        mk = rest[0] if rest else None
        N, C, H, W = xv.shape
        Co, Cg, kh, kw = wv.shape
        st = (stride, stride) if isinstance(stride, int) else stride
        pd = (padding, padding) if isinstance(padding, int) else padding
        dl = (dilation, dilation) if isinstance(dilation, int) \
            else dilation
        Ho = (H + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        Wo = (W + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        xp = jnp.pad(xv, ((0, 0), (0, 0), (pd[0], pd[0]),
                          (pd[1], pd[1])))
        base_y = (jnp.arange(Ho) * st[0])[:, None, None, None] + \
            (jnp.arange(kh) * dl[0])[None, None, :, None]
        base_x = (jnp.arange(Wo) * st[1])[None, :, None, None] + \
            (jnp.arange(kw) * dl[1])[None, None, None, :]
        off = off.reshape(N, deformable_groups, kh * kw, 2, Ho, Wo)
        oy = off[:, :, :, 0].transpose(0, 1, 3, 4, 2).reshape(
            N, deformable_groups, Ho, Wo, kh, kw)
        ox = off[:, :, :, 1].transpose(0, 1, 3, 4, 2).reshape(
            N, deformable_groups, Ho, Wo, kh, kw)
        py = base_y[None, None] + oy               # [N,G,Ho,Wo,kh,kw]
        px = base_x[None, None] + ox
        Hp, Wp = xp.shape[-2:]
        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy = py - y0
        wx = px - x0

        def samp(yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, Hp - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, Wp - 1)
            ok = (yy >= 0) & (yy <= Hp - 1) & (xx >= 0) & (xx <= Wp - 1)
            # per deformable group, channels split evenly
            cg = C // deformable_groups
            xg = xp.reshape(N, deformable_groups, cg, Hp, Wp)

            def g1(img, yi1, xi1):
                return img[:, yi1, xi1]            # [cg, ...]
            g = jax.vmap(jax.vmap(g1))(             # over N, G
                xg, yi, xi)                        # [N,G,cg,Ho,Wo,kh,kw]
            return g * ok[:, :, None].astype(xv.dtype)

        v = (samp(y0, x0) * ((1 - wy) * (1 - wx))[:, :, None]
             + samp(y0, x0 + 1) * ((1 - wy) * wx)[:, :, None]
             + samp(y0 + 1, x0) * (wy * (1 - wx))[:, :, None]
             + samp(y0 + 1, x0 + 1) * (wy * wx)[:, :, None])
        if mk is not None:
            m = mk.reshape(N, deformable_groups, kh * kw, Ho, Wo)
            m = m.transpose(0, 1, 3, 4, 2).reshape(
                N, deformable_groups, Ho, Wo, kh, kw)
            v = v * m[:, :, None]
        v = v.reshape(N, C, Ho, Wo, kh, kw)
        out = jnp.einsum("nchwij,ocij->nohw",
                         v.astype(jnp.float32),
                         wv.astype(jnp.float32))
        return out.astype(xv.dtype)
    args = (x, targ(offset), targ(weight))
    if mask is not None:
        args = args + (targ(mask),)
    return apply_op("deformable_conv", fn, args)


_DET_OPS = [
    ("box_coder", box_coder), ("prior_box", prior_box),
    ("yolo_box", yolo_box), ("yolo_loss", yolo_loss),
    ("matrix_nms", matrix_nms), ("multiclass_nms3", multiclass_nms3),
    ("generate_proposals", generate_proposals),
    ("distribute_fpn_proposals", distribute_fpn_proposals),
    ("psroi_pool", psroi_pool), ("deformable_conv", deformable_conv),
]


def register_detection_ops():
    from ..ops.registry import register, registered_ops
    for name, fn in _DET_OPS:
        if name not in registered_ops():
            register(name, fn, category="detection")
