"""Vision zoo tail: SqueezeNet, MobileNetV1/V3, ShuffleNetV2, DenseNet,
InceptionV3, GoogLeNet, wide ResNets.

Parity: python/paddle/vision/models/{squeezenet,mobilenetv1,mobilenetv3,
shufflenetv2,densenet,inceptionv3,googlenet}.py (reference).  Written
TPU-first over paddle_tpu.nn (NCHW convs lower to XLA convolutions that
tile onto the MXU); pretrained weights are unsupported in this
environment (no egress) — load explicitly with set_state_dict."""
from __future__ import annotations

from typing import List, Optional

from ... import nn


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError(
            "pretrained=True is unsupported in this environment (no "
            "network egress); load weights explicitly with set_state_dict")


def _conv_bn(ic, oc, k, s=1, p=0, groups=1, act="relu"):
    layers = [nn.Conv2D(ic, oc, k, stride=s, padding=p, groups=groups,
                        bias_attr=False),
              nn.BatchNorm2D(oc)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "hardswish":
        layers.append(nn.Hardswish())
    return nn.Sequential(*layers)


# ---------------------------------------------------------------------------
# SqueezeNet (squeezenet.py)
# ---------------------------------------------------------------------------
class _Fire(nn.Layer):
    def __init__(self, ic, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(ic, squeeze, 1)
        self.relu = nn.ReLU()
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        import paddle_tpu as paddle
        s = self.relu(self.squeeze(x))
        return paddle.concat(
            [self.relu(self.expand1(s)), self.relu(self.expand3(s))],
            axis=1)


class SqueezeNet(nn.Layer):
    """Parity: squeezenet.py SqueezeNet (version 1.0 / 1.1)."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        import paddle_tpu as paddle
        x = self.classifier(self.features(x))
        return paddle.flatten(x, 1)


def squeezenet1_0(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.1", **kwargs)


# ---------------------------------------------------------------------------
# MobileNetV1 (mobilenetv1.py)
# ---------------------------------------------------------------------------
class MobileNetV1(nn.Layer):
    """Parity: mobilenetv1.py — depthwise-separable stacks with a width
    multiplier ``scale``."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes

        def c(ch):
            return max(8, int(ch * scale))

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 \
            + [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, c(32), 3, s=2, p=1)]
        for ic, oc, s in cfg:
            layers.append(_conv_bn(c(ic), c(ic), 3, s=s, p=1,
                                   groups=c(ic)))       # depthwise
            layers.append(_conv_bn(c(ic), c(oc), 1))    # pointwise
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.fc = nn.Linear(c(1024), num_classes) if num_classes > 0 \
            else None

    def forward(self, x):
        import paddle_tpu as paddle
        x = self.features(x)
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(paddle.flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# MobileNetV3 (mobilenetv3.py)
# ---------------------------------------------------------------------------
class _SE(nn.Layer):
    def __init__(self, ch, squeeze):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, squeeze, 1)
        self.fc2 = nn.Conv2D(squeeze, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidualV3(nn.Layer):
    def __init__(self, ic, exp, oc, k, s, se, act):
        super().__init__()
        self.use_res = s == 1 and ic == oc
        blocks = []
        if exp != ic:
            blocks.append(_conv_bn(ic, exp, 1, act=act))
        blocks.append(_conv_bn(exp, exp, k, s=s, p=k // 2, groups=exp,
                               act=act))
        if se:
            blocks.append(_SE(exp, exp // 4))
        blocks.append(_conv_bn(exp, oc, 1, act="none"))
        self.block = nn.Sequential(*blocks)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_SMALL = [  # k, exp, oc, se, act, s
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1)]
_V3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1)]


class MobileNetV3(nn.Layer):
    """Parity: mobilenetv3.py MobileNetV3Small/Large."""

    def __init__(self, config, last_ch, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes

        def c(ch):
            return max(8, int(ch * scale + 4) // 8 * 8)

        layers = [_conv_bn(3, c(16), 3, s=2, p=1, act="hardswish")]
        ic = c(16)
        for k, exp, oc, se, act, s in config:
            layers.append(_InvertedResidualV3(ic, c(exp), c(oc), k, s,
                                              se, act))
            ic = c(oc)
        last_exp = c(config[-1][1])
        layers.append(_conv_bn(ic, last_exp, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))
        else:
            self.classifier = None

    def forward(self, x):
        import paddle_tpu as paddle
        x = self.features(x)
        if self.pool is not None:
            x = self.pool(x)
        if self.classifier is not None:
            x = self.classifier(paddle.flatten(x, 1))
        return x


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# ShuffleNetV2 (shufflenetv2.py)
# ---------------------------------------------------------------------------
class _ShuffleUnit(nn.Layer):
    def __init__(self, ic, oc, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = oc // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(ic // 2, branch, 1, act=act),
                _conv_bn(branch, branch, 3, s=1, p=1, groups=branch,
                         act="none"),
                _conv_bn(branch, branch, 1, act=act))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(ic, ic, 3, s=stride, p=1, groups=ic, act="none"),
                _conv_bn(ic, branch, 1, act=act))
            self.branch2 = nn.Sequential(
                _conv_bn(ic, branch, 1, act=act),
                _conv_bn(branch, branch, 3, s=stride, p=1, groups=branch,
                         act="none"),
                _conv_bn(branch, branch, 1, act=act))
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        import paddle_tpu as paddle
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)],
                                axis=1)
        return self.shuffle(out)


_SHUFFLE_CH = {0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
               0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464,
                                                   1024],
               1.5: [24, 176, 352, 704, 1024],
               2.0: [24, 244, 488, 976, 2048]}


class ShuffleNetV2(nn.Layer):
    """Parity: shufflenetv2.py."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        ch = _SHUFFLE_CH[scale]
        self.conv1 = _conv_bn(3, ch[0], 3, s=2, p=1, act=act)
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        ic = ch[0]
        for stage_idx, repeat in enumerate((4, 8, 4)):
            oc = ch[stage_idx + 1]
            stages.append(_ShuffleUnit(ic, oc, 2, act))
            for _ in range(repeat - 1):
                stages.append(_ShuffleUnit(oc, oc, 1, act))
            ic = oc
        self.stages = nn.Sequential(*stages)
        self.conv5 = _conv_bn(ic, ch[-1], 1, act=act)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.fc = nn.Linear(ch[-1], num_classes) if num_classes > 0 \
            else None

    def forward(self, x):
        import paddle_tpu as paddle
        x = self.conv5(self.stages(self.pool1(self.conv1(x))))
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(paddle.flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2(2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2(1.0, act="swish", **kw)


# ---------------------------------------------------------------------------
# DenseNet (densenet.py)
# ---------------------------------------------------------------------------
class _DenseLayer(nn.Layer):
    def __init__(self, ic, growth, bn_size, drop):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(ic)
        self.conv1 = nn.Conv2D(ic, bn_size * growth, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()
        self.drop = nn.Dropout(drop) if drop else None

    def forward(self, x):
        import paddle_tpu as paddle
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.drop is not None:
            out = self.drop(out)
        return paddle.concat([x, out], axis=1)


_DENSE_CFG = {121: (64, 32, (6, 12, 24, 16)),
              161: (96, 48, (6, 12, 36, 24)),
              169: (64, 32, (6, 12, 32, 32)),
              201: (64, 32, (6, 12, 48, 32)),
              264: (64, 32, (6, 12, 64, 48))}


class DenseNet(nn.Layer):
    """Parity: densenet.py DenseNet."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        init_ch, growth, blocks = _DENSE_CFG[layers]
        feats = [nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_ch), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        ch = init_ch
        for bi, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(blocks) - 1:       # transition
                feats += [nn.BatchNorm2D(ch), nn.ReLU(),
                          nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, stride=2)]
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.fc = nn.Linear(ch, num_classes) if num_classes > 0 else None

    def forward(self, x):
        import paddle_tpu as paddle
        x = self.features(x)
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(paddle.flatten(x, 1))
        return x


def densenet121(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return DenseNet(264, **kw)


# ---------------------------------------------------------------------------
# InceptionV3 (inceptionv3.py)
# ---------------------------------------------------------------------------
class _IncA(nn.Layer):
    def __init__(self, ic, pool_ch):
        super().__init__()
        self.b1 = _conv_bn(ic, 64, 1)
        self.b5 = nn.Sequential(_conv_bn(ic, 48, 1),
                                _conv_bn(48, 64, 5, p=2))
        self.b3 = nn.Sequential(_conv_bn(ic, 64, 1),
                                _conv_bn(64, 96, 3, p=1),
                                _conv_bn(96, 96, 3, p=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _conv_bn(ic, pool_ch, 1))

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b1(x), self.b5(x), self.b3(x),
                              self.bp(x)], axis=1)


class _IncB(nn.Layer):       # grid reduction 35 -> 17
    def __init__(self, ic):
        super().__init__()
        self.b3 = _conv_bn(ic, 384, 3, s=2)
        self.b33 = nn.Sequential(_conv_bn(ic, 64, 1),
                                 _conv_bn(64, 96, 3, p=1),
                                 _conv_bn(96, 96, 3, s=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b3(x), self.b33(x), self.pool(x)],
                             axis=1)


class _IncC(nn.Layer):       # 17x17 factorized 7x7
    def __init__(self, ic, ch7):
        super().__init__()
        self.b1 = _conv_bn(ic, 192, 1)
        self.b7 = nn.Sequential(
            _conv_bn(ic, ch7, 1), _conv_bn(ch7, ch7, (1, 7), p=(0, 3)),
            _conv_bn(ch7, 192, (7, 1), p=(3, 0)))
        self.b77 = nn.Sequential(
            _conv_bn(ic, ch7, 1), _conv_bn(ch7, ch7, (7, 1), p=(3, 0)),
            _conv_bn(ch7, ch7, (1, 7), p=(0, 3)),
            _conv_bn(ch7, ch7, (7, 1), p=(3, 0)),
            _conv_bn(ch7, 192, (1, 7), p=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _conv_bn(ic, 192, 1))

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b1(x), self.b7(x), self.b77(x),
                              self.bp(x)], axis=1)


class _IncD(nn.Layer):       # grid reduction 17 -> 8
    def __init__(self, ic):
        super().__init__()
        self.b3 = nn.Sequential(_conv_bn(ic, 192, 1),
                                _conv_bn(192, 320, 3, s=2))
        self.b7 = nn.Sequential(
            _conv_bn(ic, 192, 1), _conv_bn(192, 192, (1, 7), p=(0, 3)),
            _conv_bn(192, 192, (7, 1), p=(3, 0)),
            _conv_bn(192, 192, 3, s=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)],
                             axis=1)


class _IncE(nn.Layer):       # 8x8 expanded
    def __init__(self, ic):
        super().__init__()
        self.b1 = _conv_bn(ic, 320, 1)
        self.b3_stem = _conv_bn(ic, 384, 1)
        self.b3_a = _conv_bn(384, 384, (1, 3), p=(0, 1))
        self.b3_b = _conv_bn(384, 384, (3, 1), p=(1, 0))
        self.b33_stem = nn.Sequential(_conv_bn(ic, 448, 1),
                                      _conv_bn(448, 384, 3, p=1))
        self.b33_a = _conv_bn(384, 384, (1, 3), p=(0, 1))
        self.b33_b = _conv_bn(384, 384, (3, 1), p=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _conv_bn(ic, 192, 1))

    def forward(self, x):
        import paddle_tpu as paddle
        s3 = self.b3_stem(x)
        s33 = self.b33_stem(x)
        return paddle.concat(
            [self.b1(x), self.b3_a(s3), self.b3_b(s3),
             self.b33_a(s33), self.b33_b(s33), self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """Parity: inceptionv3.py InceptionV3 (299x299 input)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _conv_bn(3, 32, 3, s=2), _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, p=1), nn.MaxPool2D(3, stride=2),
            _conv_bn(64, 80, 1), _conv_bn(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160),
            _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048))
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.dropout = nn.Dropout(0.5)
        self.fc = nn.Linear(2048, num_classes) if num_classes > 0 \
            else None

    def forward(self, x):
        import paddle_tpu as paddle
        x = self.blocks(self.stem(x))
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(self.dropout(paddle.flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return InceptionV3(**kw)


# ---------------------------------------------------------------------------
# GoogLeNet (googlenet.py — inception v1 with two aux heads)
# ---------------------------------------------------------------------------
class _IncV1(nn.Layer):
    def __init__(self, ic, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(ic, c1, 1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(ic, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1),
                                nn.ReLU())
        self.b5 = nn.Sequential(nn.Conv2D(ic, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2),
                                nn.ReLU())
        self.bp = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(ic, pp, 1), nn.ReLU())

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b1(x), self.b3(x), self.b5(x),
                              self.bp(x)], axis=1)


class GoogLeNet(nn.Layer):
    """Parity: googlenet.py — returns (out, aux1, aux2) like the
    reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, ceil_mode=True))
        self.inc3 = nn.Sequential(
            _IncV1(192, 64, 96, 128, 16, 32, 32),
            _IncV1(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, stride=2, ceil_mode=True))
        self.inc4a = _IncV1(480, 192, 96, 208, 16, 48, 64)
        self.inc4bcd = nn.Sequential(
            _IncV1(512, 160, 112, 224, 24, 64, 64),
            _IncV1(512, 128, 128, 256, 24, 64, 64),
            _IncV1(512, 112, 144, 288, 32, 64, 64))
        self.inc4e = _IncV1(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.inc5 = nn.Sequential(
            _IncV1(832, 256, 160, 320, 32, 128, 128),
            _IncV1(832, 384, 192, 384, 48, 128, 128))

        def aux(ic):
            return nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Conv2D(ic, 128, 1),
                nn.ReLU(), nn.Flatten(),
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))

        self.aux1 = aux(512)
        self.aux2 = aux(528)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        import paddle_tpu as paddle
        x = self.inc4a(self.inc3(self.stem(x)))
        a1 = self.aux1(x)
        x = self.inc4bcd(x)
        a2 = self.aux2(x)
        x = self.inc5(self.pool4(self.inc4e(x)))
        out = self.fc(self.dropout(paddle.flatten(self.pool(x), 1)))
        return out, a1, a2


def googlenet(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return GoogLeNet(**kw)
