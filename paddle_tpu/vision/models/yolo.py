"""YOLOv3-tiny-class one-stage detector assembled from the core
detection ops.

Parity note: the reference keeps full detectors (PP-YOLOE, Mask R-CNN)
in the external PaddleDetection repo; core paddle ships the OPS —
yolo_box / yolo_loss / nms (reference:
python/paddle/vision/ops.py:1168 yolo_loss, :1374 yolo_box) — which
this framework implements in paddle_tpu/vision/detection.py.  This
module assembles those ops into the standard tiny-YOLOv3 architecture
(backbone conv-BN-leaky stack + two detection heads with a routed
upsample, anchors/masks from the darknet config) so the detector
training pipeline — DataLoader -> HBM -> fused train step over
yolo_loss — is exercised end to end (BASELINE.json configs[2]).
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...nn import functional as F
from ...ops.manipulation import concat
from ..detection import yolo_box, yolo_loss, multiclass_nms3

__all__ = ["YOLOv3Tiny", "yolov3_tiny"]

# darknet yolov3-tiny anchors (pixel units at 416 input; scale-free in
# the loss because boxes are normalized by downsample_ratio * grid)
_ANCHORS = (10, 14, 23, 27, 37, 58, 81, 82, 135, 169, 344, 319)
_MASKS = ((3, 4, 5), (0, 1, 2))


class _ConvBN(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=k // 2, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)

    def forward(self, x):
        return F.leaky_relu(self.bn(self.conv(x)), 0.1)


class YOLOv3Tiny(nn.Layer):
    """Two-scale tiny detector: strides 32 and 16, 3 anchors each."""

    def __init__(self, num_classes=80):
        super().__init__()
        self.num_classes = num_classes
        ch = (16, 32, 64, 128, 256)
        self.stem = nn.LayerList()
        cin = 3
        for c in ch:
            self.stem.append(_ConvBN(cin, c))
            cin = c
        # route tap after the 256 stage (stride 16)
        self.deep = _ConvBN(256, 512)
        self.neck = _ConvBN(512, 256, k=1)
        na = len(_MASKS[0])
        cout = na * (5 + num_classes)
        self.head32_conv = _ConvBN(256, 512)
        self.head32 = nn.Conv2D(512, cout, 1)
        self.route = _ConvBN(256, 128, k=1)
        self.head16_conv = _ConvBN(128 + 256, 256)
        self.head16 = nn.Conv2D(256, cout, 1)

    def forward(self, x):
        for i, blk in enumerate(self.stem):
            x = blk(x)
            # pool after every stage except the last (stride 16 tap)
            if i < len(self.stem) - 1:
                x = F.max_pool2d(x, 2, stride=2)
        tap16 = x                                  # stride 16
        x = F.max_pool2d(x, 2, stride=2)
        x = self.neck(self.deep(x))                # stride 32
        p32 = self.head32(self.head32_conv(x))
        up = F.interpolate(self.route(x), scale_factor=2,
                           mode="nearest")
        p16 = self.head16(self.head16_conv(concat([up, tap16], axis=1)))
        return [p32, p16]

    def loss(self, outputs, gt_box, gt_label):
        """Sum of per-scale yolo_loss (reference yolo_loss semantics:
        gt_box normalized xywh, labels int)."""
        total = None
        for out, mask, ds in zip(outputs, _MASKS, (32, 16)):
            l = yolo_loss(out, gt_box, gt_label, anchors=_ANCHORS,
                          anchor_mask=mask, class_num=self.num_classes,
                          downsample_ratio=ds, use_label_smooth=False)
            l = l.sum() if hasattr(l, "sum") else l
            total = l if total is None else total + l
        return total

    def decode(self, outputs, img_size, conf_thresh=0.05,
               nms_threshold=0.45):
        """Inference path: yolo_box per scale + multiclass NMS."""
        boxes, scores = [], []
        for out, mask, ds in zip(outputs, _MASKS, (32, 16)):
            an = [v for i in mask
                  for v in _ANCHORS[2 * i:2 * i + 2]]
            b, s = yolo_box(out, img_size, anchors=an,
                            class_num=self.num_classes,
                            conf_thresh=conf_thresh,
                            downsample_ratio=ds)
            boxes.append(b)
            scores.append(s)
        bx = concat(boxes, axis=1)
        sc = concat(scores, axis=1).transpose([0, 2, 1])
        # background_label=-1: sigmoid class heads have no background
        # class (default 0 would silently drop every class-0 box)
        return multiclass_nms3(bx, sc, score_threshold=conf_thresh,
                               nms_threshold=nms_threshold,
                               background_label=-1)


def yolov3_tiny(num_classes=80, **kwargs):
    return YOLOv3Tiny(num_classes=num_classes, **kwargs)
