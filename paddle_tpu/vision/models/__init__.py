"""Vision model zoo (parity: python/paddle/vision/models/ — LeNet, AlexNet,
VGG, ResNet variants, MobileNetV2, GoogLeNet shim).

ResNet lives in ``paddle_tpu.models.resnet`` (the benchmark model) and is
re-exported here under the reference names. ``pretrained=True`` is not
supported (no network egress) and raises.
"""
from __future__ import annotations

from ... import nn
from ...models.resnet import (ResNet, BasicBlock, BottleneckBlock, resnet18,
                              resnet34, resnet50, resnet101, resnet152,
                              wide_resnet50_2, wide_resnet101_2,
                              resnext50_32x4d, resnext50_64x4d,
                              resnext101_32x4d, resnext101_64x4d,
                              resnext152_32x4d, resnext152_64x4d)
from .extra import (SqueezeNet, squeezenet1_0, squeezenet1_1,
                    MobileNetV1, mobilenet_v1,
                    MobileNetV3Small, MobileNetV3Large,
                    mobilenet_v3_small, mobilenet_v3_large,
                    ShuffleNetV2, shufflenet_v2_x0_25,
                    shufflenet_v2_x0_33, shufflenet_v2_x0_5,
                    shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                    shufflenet_v2_x2_0, shufflenet_v2_swish,
                    DenseNet, densenet121, densenet161, densenet169,
                    densenet201, densenet264,
                    InceptionV3, inception_v3, GoogLeNet, googlenet)

__all__ = ["LeNet", "AlexNet", "alexnet", "VGG", "vgg11", "vgg13", "vgg16",
           "vgg19", "MobileNetV2", "mobilenet_v2", "ResNet", "resnet18",
           "resnet34", "resnet50", "resnet101", "resnet152",
           "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
           "MobileNetV1", "mobilenet_v1", "MobileNetV3Small",
           "MobileNetV3Large", "mobilenet_v3_small", "mobilenet_v3_large",
           "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish", "DenseNet", "densenet121",
           "densenet161", "densenet169", "densenet201", "densenet264",
           "InceptionV3", "inception_v3", "GoogLeNet", "googlenet",
           "wide_resnet50_2", "wide_resnet101_2", "resnext50_32x4d",
           "resnext50_64x4d", "resnext101_32x4d", "resnext101_64x4d",
           "resnext152_32x4d", "resnext152_64x4d"]


from .extra import _no_pretrained  # single definition, shared


class LeNet(nn.Layer):
    """Parity: python/paddle/vision/models/lenet.py."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
        )
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120),
                nn.Linear(120, 84),
                nn.Linear(84, num_classes),
            )

    def forward(self, inputs):
        x = self.features(inputs)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.fc(x)
        return x


class AlexNet(nn.Layer):
    """Parity: python/paddle/vision/models/alexnet.py."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2),
        )
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
            nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.features(x)
        x = self.avgpool(x)
        x = nn.Flatten()(x)
        return self.classifier(x)


def alexnet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return AlexNet(**kwargs)


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Layer):
    """Parity: python/paddle/vision/models/vgg.py."""

    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes),
            )
        self.num_classes = num_classes

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.classifier(x)
        return x


def _make_vgg_layers(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


def _vgg(cfg, batch_norm, pretrained, **kwargs):
    _no_pretrained(pretrained)
    return VGG(_make_vgg_layers(_VGG_CFGS[cfg], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kw):
    return _vgg("A", batch_norm, pretrained, **kw)


def vgg13(pretrained=False, batch_norm=False, **kw):
    return _vgg("B", batch_norm, pretrained, **kw)


def vgg16(pretrained=False, batch_norm=False, **kw):
    return _vgg("D", batch_norm, pretrained, **kw)


def vgg19(pretrained=False, batch_norm=False, **kw):
    return _vgg("E", batch_norm, pretrained, **kw)


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers += [nn.Conv2D(inp, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), nn.ReLU6()]
        layers += [
            nn.Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                      groups=hidden, bias_attr=False),
            nn.BatchNorm2D(hidden), nn.ReLU6(),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """Parity: python/paddle/vision/models/mobilenetv2.py."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [
            # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        input_channel = int(32 * scale)
        features = [nn.Conv2D(3, input_channel, 3, stride=2, padding=1,
                              bias_attr=False),
                    nn.BatchNorm2D(input_channel), nn.ReLU6()]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                features.append(_InvertedResidual(
                    input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        self.last_channel = int(1280 * max(1.0, scale))
        features += [nn.Conv2D(input_channel, self.last_channel, 1,
                               bias_attr=False),
                     nn.BatchNorm2D(self.last_channel), nn.ReLU6()]
        self.features = nn.Sequential(*features)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    _no_pretrained(pretrained)
    return MobileNetV2(scale=scale, **kw)

from .vit import VisionTransformer, vit_b_16, vit_s_16, vit_tiny  # noqa: E402

__all__ += ["VisionTransformer", "vit_b_16", "vit_s_16", "vit_tiny"]
