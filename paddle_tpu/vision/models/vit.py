"""Vision Transformer (parity family: the reference ecosystem's ViT in
PaddleClas / paddle.vision model-zoo style — patch embedding via conv,
class token + learned positions, pre-norm encoder blocks, linear head).

TPU-native: the encoder rides paddle_tpu.nn.TransformerEncoderLayer
(flash-attention SDPA under the hood) so the same kernels serve NLP and
vision; patchify is one Conv2D that XLA maps onto the MXU.
"""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, transpose, expand

__all__ = ["VisionTransformer", "vit_b_16", "vit_s_16", "vit_tiny"]


class VisionTransformer(nn.Layer):
    def __init__(self, image_size=224, patch_size=16, in_channels=3,
                 embed_dim=768, depth=12, num_heads=12, mlp_ratio=4.0,
                 dropout=0.0, num_classes=1000):
        super().__init__()
        if image_size % patch_size:
            raise ValueError("image_size must be divisible by patch_size")
        self.patch_embed = nn.Conv2D(in_channels, embed_dim, patch_size,
                                     stride=patch_size)
        n_patches = (image_size // patch_size) ** 2
        self.cls_token = self.create_parameter(
            [1, 1, embed_dim], default_initializer=nn.initializer.Normal(
                0.0, 0.02))
        self.pos_embed = self.create_parameter(
            [1, n_patches + 1, embed_dim],
            default_initializer=nn.initializer.Normal(0.0, 0.02))
        self.dropout = nn.Dropout(dropout)
        self.blocks = nn.LayerList([
            nn.TransformerEncoderLayer(
                embed_dim, num_heads, int(embed_dim * mlp_ratio),
                dropout=dropout, activation="gelu", normalize_before=True)
            for _ in range(depth)])
        self.norm = nn.LayerNorm(embed_dim)
        self.head = nn.Linear(embed_dim, num_classes) \
            if num_classes > 0 else None

    def forward(self, x):
        B = x.shape[0]
        x = self.patch_embed(x)                   # [B, E, H/p, W/p]
        x = x.flatten(2)                          # [B, E, N]
        x = transpose(x, [0, 2, 1])               # [B, N, E]
        cls = expand(self.cls_token, [B, 1, x.shape[-1]])
        x = concat([cls, x], axis=1) + self.pos_embed
        x = self.dropout(x)
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        if self.head is None:
            return x
        return self.head(x[:, 0])


def _no_pretrained(pretrained):
    # vit.py is imported at the end of the package __init__, so the
    # shared helper is already defined there — one policy, one message
    from . import _no_pretrained as _impl
    _impl(pretrained)


def vit_b_16(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return VisionTransformer(embed_dim=768, depth=12, num_heads=12,
                             patch_size=16, **kwargs)


def vit_s_16(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return VisionTransformer(embed_dim=384, depth=12, num_heads=6,
                             patch_size=16, **kwargs)


def vit_tiny(pretrained=False, **kwargs):
    """Small config for tests/CPU."""
    _no_pretrained(pretrained)
    kwargs.setdefault("image_size", 32)
    kwargs.setdefault("patch_size", 8)
    kwargs.setdefault("num_classes", 10)
    return VisionTransformer(embed_dim=64, depth=2, num_heads=2, **kwargs)
