"""Composable image transforms (parity:
python/paddle/vision/transforms/transforms.py).
"""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np
from PIL import Image

from . import functional as F
from .functional import *      # noqa: F401,F403

__all__ = ["BaseTransform", "Compose", "Resize", "RandomResizedCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Normalize", "BrightnessTransform",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "ColorJitter", "RandomCrop", "Pad", "RandomRotation",
           "Grayscale", "ToTensor", "RandomErasing", "RandomAffine",
           "RandomPerspective"] + list(F.__all__)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for f in self.transforms:
            data = f(data)
        return data

    def __repr__(self):
        return "Compose(%s)" % ", ".join(repr(t) for t in self.transforms)


class BaseTransform:
    """Transform base with (optional) keys routing like the reference."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            outputs = []
            for i, key in enumerate(self.keys):
                if i >= len(inputs):
                    break
                if key == "image":
                    outputs.append(self._apply_image(inputs[i]))
                else:
                    outputs.append(inputs[i])
            outputs.extend(inputs[len(self.keys):])
            return tuple(outputs) if len(outputs) > 1 else outputs[0]
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError

    def __repr__(self):
        return self.__class__.__name__


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _get_param(self, img):
        if isinstance(img, Image.Image):
            w, h = img.size
        else:
            h, w = np.asarray(img).shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            aspect = np.exp(random.uniform(*log_ratio))
            tw = int(round(np.sqrt(target_area * aspect)))
            th = int(round(np.sqrt(target_area / aspect)))
            if 0 < tw <= w and 0 < th <= h:
                top = random.randint(0, h - th)
                left = random.randint(0, w - tw)
                return top, left, th, tw
        # fallback: center crop
        in_ratio = w / h
        if in_ratio < self.ratio[0]:
            tw, th = w, int(round(w / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            th, tw = h, int(round(h * self.ratio[1]))
        else:
            tw, th = w, h
        return (h - th) // 2, (w - tw) // 2, th, tw

    def _apply_image(self, img):
        top, left, th, tw = self._get_param(img)
        img = F.crop(img, top, left, th, tw)
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format
        self.to_rgb = to_rgb

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format,
                           self.to_rgb)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return F.adjust_brightness(
            img, random.uniform(max(0, 1 - self.value), 1 + self.value))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return F.adjust_contrast(
            img, random.uniform(max(0, 1 - self.value), 1 + self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return F.adjust_saturation(
            img, random.uniform(max(0, 1 - self.value), 1 + self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0.0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return F.adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        if isinstance(img, Image.Image):
            w, h = img.size
        else:
            h, w = np.asarray(img).shape[:2]
        th, tw = self.size
        if self.pad_if_needed and w < tw:
            img = F.pad(img, (tw - w, 0), self.fill, self.padding_mode)
            w = tw
        if self.pad_if_needed and h < th:
            img = F.pad(img, (0, th - h), self.fill, self.padding_mode)
            h = th
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be positive if scalar")
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        arr = np.asarray(img)
        if arr.ndim == 2:
            h, w = arr.shape
        elif isinstance(img, Image.Image) or arr.shape[-1] in (1, 3, 4):
            h, w = arr.shape[:2]
        else:                      # CHW tensor
            h, w = arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / aspect)))
            ew = int(round(np.sqrt(target * aspect)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                return F.erase(img, top, left, eh, ew, self.value,
                               self.inplace)
        return img


class RandomAffine(BaseTransform):
    """Parity: paddle.vision.transforms.RandomAffine — random rotation/
    translation/scale/shear within the given ranges."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, (int, float)):
            degrees = (-abs(float(degrees)), abs(float(degrees)))
        self.degrees = tuple(degrees)
        self.translate = translate
        self.scale = scale
        if isinstance(shear, (int, float)):
            shear = (-abs(float(shear)), abs(float(shear)))
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _get_param(self, img_size):
        import random
        w, h = img_size
        angle = random.uniform(*self.degrees)
        if self.translate is not None:
            max_dx = self.translate[0] * w
            max_dy = self.translate[1] * h
            translate = (random.uniform(-max_dx, max_dx),
                         random.uniform(-max_dy, max_dy))
        else:
            translate = (0.0, 0.0)
        scale = random.uniform(*self.scale) if self.scale is not None             else 1.0
        if self.shear is not None:
            sh = list(self.shear)
            shear_x = random.uniform(sh[0], sh[1])
            shear_y = random.uniform(sh[2], sh[3]) if len(sh) == 4                 else 0.0
            shear = (shear_x, shear_y)
        else:
            shear = (0.0, 0.0)
        return angle, translate, scale, shear

    def _apply_image(self, img):
        size = img.size if F._is_pil(img) else             (np.asarray(img).shape[-2], np.asarray(img).shape[-3])             if not F._is_pil(img) else None
        if F._is_pil(img):
            w, h = img.size
        else:
            a = np.asarray(img._value if F._is_tensor(img) else img)
            h, w = (a.shape[-2], a.shape[-1]) if a.shape[0] in (1, 3)                 and a.ndim == 3 and F._is_tensor(img) else                 (a.shape[0], a.shape[1])
        angle, translate, scale, shear = self._get_param((w, h))
        return F.affine(img, angle, translate, scale, shear,
                        self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    """Parity: paddle.vision.transforms.RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _get_param(self, width, height):
        import random
        d = self.distortion_scale
        half_w, half_h = width // 2, height // 2
        tl = (random.randint(0, int(d * half_w)),
              random.randint(0, int(d * half_h)))
        tr = (random.randint(width - int(d * half_w) - 1, width - 1),
              random.randint(0, int(d * half_h)))
        br = (random.randint(width - int(d * half_w) - 1, width - 1),
              random.randint(height - int(d * half_h) - 1, height - 1))
        bl = (random.randint(0, int(d * half_w)),
              random.randint(height - int(d * half_h) - 1, height - 1))
        start = [(0, 0), (width - 1, 0), (width - 1, height - 1),
                 (0, height - 1)]
        return start, [tl, tr, br, bl]

    def _apply_image(self, img):
        import random
        if random.random() >= self.prob:
            return img
        if F._is_pil(img):
            w, h = img.size
        else:
            a = np.asarray(img._value if F._is_tensor(img) else img)
            h, w = (a.shape[-2], a.shape[-1]) if F._is_tensor(img)                 and a.ndim == 3 and a.shape[0] in (1, 3) else                 (a.shape[0], a.shape[1])
        start, end = self._get_param(w, h)
        return F.perspective(img, start, end, self.interpolation,
                             self.fill)
