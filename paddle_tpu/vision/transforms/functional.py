"""Functional image transforms (parity:
python/paddle/vision/transforms/functional.py).

TPU-native stance: transforms are host-side input-pipeline work (they feed
the device, they don't run on it), so they operate on PIL Images and numpy
HWC arrays and stay out of the traced graph. ``to_tensor`` is the
host→device boundary.
"""
from __future__ import annotations

import numbers
from typing import List, Sequence

import numpy as np
from PIL import Image, ImageEnhance, ImageOps

from ...core.tensor import Tensor

__all__ = ["to_tensor", "hflip", "vflip", "resize", "pad", "crop",
           "center_crop", "adjust_brightness", "adjust_contrast",
           "adjust_hue", "adjust_saturation", "rotate", "to_grayscale",
           "normalize", "erase", "affine", "perspective"]

_PIL_MODES = {
    "nearest": Image.NEAREST,
    "bilinear": Image.BILINEAR,
    "bicubic": Image.BICUBIC,
    "box": Image.BOX,
    "lanczos": Image.LANCZOS,
    "hamming": Image.HAMMING,
}


def _is_pil(img):
    return isinstance(img, Image.Image)


def _is_numpy(img):
    return isinstance(img, np.ndarray)


def _is_tensor(img):
    return isinstance(img, Tensor)


def to_tensor(pic, data_format="CHW"):
    """PIL/ndarray HWC uint8 → float32 Tensor scaled to [0,1] (uint8 only)."""
    if _is_tensor(pic):
        return pic
    if _is_pil(pic):
        arr = np.asarray(pic)
        if arr.ndim == 2:
            arr = arr[:, :, None]
    else:
        arr = pic
        if arr.ndim == 2:
            arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if data_format.upper() == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(np.ascontiguousarray(arr))


def _as_numpy(img):
    """Return (HWC array, restore_fn). Tensor inputs follow the reference's
    functional_tensor convention: CHW — they are moved to HWC here and moved
    back by restore_fn so all spatial code below is HWC-only."""
    if _is_pil(img):
        return np.asarray(img), None
    if _is_tensor(img):
        arr = np.asarray(img._value)
        if arr.ndim == 3:
            arr = np.moveaxis(arr, 0, 2)
            return arr, lambda a: Tensor(np.ascontiguousarray(
                np.moveaxis(a, 2, 0)))
        return arr, lambda a: Tensor(np.ascontiguousarray(a))
    return img, None


def _restore(out, restore_fn):
    return restore_fn(out) if restore_fn is not None else out


def hflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    arr, back = _as_numpy(img)
    return _restore(arr[:, ::-1, ...].copy(), back)


def vflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_TOP_BOTTOM)
    arr, back = _as_numpy(img)
    return _restore(arr[::-1, ...].copy(), back)


def _target_size(w, h, size):
    if isinstance(size, int):
        if (w <= h and w == size) or (h <= w and h == size):
            return w, h
        if w < h:
            return size, int(size * h / w)
        return int(size * w / h), size
    return size[1], size[0]   # size is (h, w)


def resize(img, size, interpolation="bilinear"):
    if _is_pil(img):
        ow, oh = _target_size(img.width, img.height, size)
        return img.resize((ow, oh), _PIL_MODES[interpolation])
    arr, back = _as_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    ow, oh = _target_size(w, h, size)
    if arr.dtype == np.uint8:
        chans = [np.asarray(Image.fromarray(arr[:, :, c]).resize(
            (ow, oh), _PIL_MODES[interpolation])) for c in range(arr.shape[2])]
        out = np.stack(chans, axis=2)
    else:
        chans = [np.asarray(Image.fromarray(
            arr[:, :, c].astype(np.float32), mode="F").resize(
            (ow, oh), _PIL_MODES[interpolation])) for c in range(arr.shape[2])]
        out = np.stack(chans, axis=2).astype(arr.dtype)
    if squeeze:
        out = out[:, :, 0]
    return _restore(out, back)


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    if _is_pil(img):
        if padding_mode == "constant":
            return ImageOps.expand(img, (left, top, right, bottom), fill=fill)
        arr = np.asarray(img)
        padded = pad(arr, padding, fill, padding_mode)
        return Image.fromarray(padded)
    arr, back = _as_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    np_mode = {"constant": "constant", "edge": "edge",
               "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    out = np.pad(arr, ((top, bottom), (left, right), (0, 0)), np_mode, **kw)
    if squeeze:
        out = out[:, :, 0]
    return _restore(out, back)


def crop(img, top, left, height, width):
    if _is_pil(img):
        return img.crop((left, top, left + width, top + height))
    arr, back = _as_numpy(img)
    return _restore(arr[top:top + height, left:left + width, ...].copy(),
                    back)


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    if _is_pil(img):
        w, h = img.size
    else:
        arr, _ = _as_numpy(img)
        h, w = arr.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def adjust_brightness(img, brightness_factor):
    if _is_pil(img):
        return ImageEnhance.Brightness(img).enhance(brightness_factor)
    arr, back = _as_numpy(img)
    dt = arr.dtype
    out = np.clip(arr.astype(np.float32) * brightness_factor, 0,
                  255 if dt == np.uint8 else np.inf).astype(dt)
    return _restore(out, back)


def adjust_contrast(img, contrast_factor):
    if _is_pil(img):
        return ImageEnhance.Contrast(img).enhance(contrast_factor)
    arr, back = _as_numpy(img)
    dt = arr.dtype
    f = arr.astype(np.float32)
    gray = f.mean() if f.ndim == 2 else (
        f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)).mean()
    out = np.clip(gray + contrast_factor * (f - gray), 0,
                  255 if dt == np.uint8 else np.inf).astype(dt)
    return _restore(out, back)


def adjust_saturation(img, saturation_factor):
    if _is_pil(img):
        return ImageEnhance.Color(img).enhance(saturation_factor)
    arr, back = _as_numpy(img)
    dt = arr.dtype
    f = arr.astype(np.float32)
    gray = (f[..., :3] @ np.array([0.299, 0.587, 0.114],
                                  np.float32))[..., None]
    out = np.clip(gray + saturation_factor * (f - gray), 0,
                  255 if dt == np.uint8 else np.inf).astype(dt)
    return _restore(out, back)


def adjust_hue(img, hue_factor):
    if not (-0.5 <= hue_factor <= 0.5):
        raise ValueError("hue_factor is not in [-0.5, 0.5].")
    arr, back = (None, None) if _is_pil(img) else _as_numpy(img)
    pil = img if _is_pil(img) else Image.fromarray(arr)
    h, s, v = pil.convert("HSV").split()
    np_h = np.asarray(h, dtype=np.uint8)
    np_h = (np_h.astype(np.int16) + int(hue_factor * 255)) % 256
    h = Image.fromarray(np_h.astype(np.uint8), "L")
    out = Image.merge("HSV", (h, s, v)).convert(pil.mode)
    if _is_pil(img):
        return out
    return _restore(np.asarray(out), back)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr, back = (None, None) if _is_pil(img) else _as_numpy(img)
    pil = img if _is_pil(img) else Image.fromarray(np.asarray(arr))
    out = pil.rotate(angle, _PIL_MODES[interpolation], expand, center,
                     fillcolor=fill)
    if _is_pil(img):
        return out
    return _restore(np.asarray(out), back)


def to_grayscale(img, num_output_channels=1):
    arr, back = (None, None) if _is_pil(img) else _as_numpy(img)
    pil = img if _is_pil(img) else Image.fromarray(np.asarray(arr))
    g = pil.convert("L")
    if num_output_channels == 3:
        g = Image.merge("RGB", (g, g, g))
    if _is_pil(img):
        return g
    out = np.asarray(g)
    if back is not None and out.ndim == 2:
        # grayscale of a CHW tensor: restore expects HWC
        out = out[:, :, None] if num_output_channels == 1 else out
    return _restore(out, back)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if _is_pil(img):
        img = np.asarray(img).astype(np.float32)
    tensor_in = _is_tensor(img)
    arr = np.asarray(img._value if tensor_in else img, dtype=np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format.upper() == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if tensor_in else out


def erase(img, i, j, h, w, v, inplace=False):
    tensor_in = _is_tensor(img)
    pil_in = _is_pil(img)
    arr = np.asarray(img) if pil_in else (
        np.asarray(img._value) if tensor_in else img)
    if not inplace or pil_in or tensor_in:
        arr = arr.copy()
    if arr.ndim == 3 and not pil_in and arr.shape[0] in (1, 3) \
            and tensor_in:
        arr[..., i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w, ...] = v
    if pil_in:
        return Image.fromarray(arr)
    return Tensor(arr) if tensor_in else arr


def _inverse_affine_matrix(center, angle, translate, scale, shear):
    """Inverse affine coefficients for PIL Image.transform (output ->
    input mapping), the standard RSS decomposition
    (reference transforms/functional.py affine; same math as the
    C++ affine_grid path)."""
    import math
    rot = math.radians(angle)
    sx = math.radians(shear[0])
    sy = math.radians(shear[1])
    cx, cy = center
    tx, ty = translate
    # RSS = rotation * shear * scale
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    # inverse of scale * RSS
    matrix = [d, -b, 0.0, -c, a, 0.0]
    matrix = [m / scale for m in matrix]
    # inverse translation: -C - T
    matrix[2] += matrix[0] * (-cx - tx) + matrix[1] * (-cy - ty)
    matrix[5] += matrix[3] * (-cx - tx) + matrix[4] * (-cy - ty)
    # recenter
    matrix[2] += cx
    matrix[5] += cy
    return matrix


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Parity: paddle.vision.transforms.affine — rotation + translation
    + isotropic scale + shear about ``center``."""
    if isinstance(shear, (int, float)):
        shear = [float(shear), 0.0]
    shear = list(shear) + [0.0] * (2 - len(list(shear)))
    arr, back = (None, None) if _is_pil(img) else _as_numpy(img)
    pil = img if _is_pil(img) else Image.fromarray(np.asarray(arr))
    w, h = pil.size
    if center is None:
        center = (w * 0.5, h * 0.5)
    coeffs = _inverse_affine_matrix(center, angle, translate, scale,
                                    shear)
    out = pil.transform((w, h), Image.AFFINE, coeffs,
                        _PIL_MODES[interpolation], fillcolor=fill)
    if _is_pil(img):
        return out
    return _restore(np.asarray(out), back)


def _perspective_coeffs(startpoints, endpoints):
    """Solve the 8-dof homography mapping endpoints -> startpoints
    (PIL wants the output->input direction)."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b += [sx, sy]
    coeffs = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(b, np.float64))
    return coeffs.tolist()


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Parity: paddle.vision.transforms.perspective — projective warp
    taking ``startpoints`` (4 corners) to ``endpoints``."""
    arr, back = (None, None) if _is_pil(img) else _as_numpy(img)
    pil = img if _is_pil(img) else Image.fromarray(np.asarray(arr))
    w, h = pil.size
    coeffs = _perspective_coeffs(startpoints, endpoints)
    out = pil.transform((w, h), Image.PERSPECTIVE, coeffs,
                        _PIL_MODES[interpolation], fillcolor=fill)
    if _is_pil(img):
        return out
    return _restore(np.asarray(out), back)
