"""Vision ops (parity: python/paddle/vision/ops.py — nms, roi_align,
roi_pool, box conversion/iou helpers, DeformConv2D is not ported).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["nms", "box_area", "box_iou", "roi_align", "roi_pool"]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def box_area(boxes):
    b = _val(boxes)
    return Tensor._from_value(
        (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def box_iou(boxes1, boxes2):
    """Pairwise IoU for [N,4] and [M,4] xyxy boxes."""
    a, b = _val(boxes1), _val(boxes2)
    area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor._from_value(inter / (area1[:, None] + area2[None] - inter))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy hard-NMS (parity: paddle.vision.ops.nms).

    Host-side: NMS is a data-dependent sequential prune used in input/output
    post-processing, not in the compiled training graph, so it runs in numpy
    (the reference's CPU kernel is also sequential).
    """
    boxes_np = np.asarray(_val(boxes))
    n = boxes_np.shape[0]
    if scores is None:
        order = np.arange(n)
    else:
        order = np.argsort(-np.asarray(_val(scores)))

    def greedy(order_idx, mask_boxes):
        keep = []
        suppressed = np.zeros(n, dtype=bool)
        x1, y1, x2, y2 = (mask_boxes[:, i] for i in range(4))
        areas = (x2 - x1) * (y2 - y1)
        for i in order_idx:
            if suppressed[i]:
                continue
            keep.append(i)
            xx1 = np.maximum(x1[i], x1)
            yy1 = np.maximum(y1[i], y1)
            xx2 = np.minimum(x2[i], x2)
            yy2 = np.minimum(y2[i], y2)
            w = np.clip(xx2 - xx1, 0, None)
            h = np.clip(yy2 - yy1, 0, None)
            inter = w * h
            iou = inter / (areas[i] + areas - inter + 1e-10)
            suppressed |= iou > iou_threshold
        return np.asarray(keep, np.int64)

    if category_idxs is None:
        keep = greedy(order, boxes_np)
    else:
        cats = np.asarray(_val(category_idxs))
        if categories is None:
            categories = np.unique(cats)
        keeps = []
        for c in categories:
            idx = np.where(cats == c)[0]
            if idx.size == 0:
                continue
            sub_order = idx[np.argsort(
                -np.asarray(_val(scores))[idx])] if scores is not None else idx
            keeps.append(greedy(sub_order, boxes_np))
        keep = np.concatenate(keeps) if keeps else np.empty(0, np.int64)
        if scores is not None:
            keep = keep[np.argsort(-np.asarray(_val(scores))[keep])]
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def _bilinear_sample(feat, ys, xs):
    """feat [C,H,W]; ys/xs flat sample coords -> [C, n]."""
    C, H, W = feat.shape
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy1 = jnp.clip(ys - y0, 0.0, 1.0)
    wx1 = jnp.clip(xs - x0, 0.0, 1.0)
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1
    f = feat.reshape(C, -1)
    idx = lambda yy, xx: f[:, yy * W + xx]        # noqa: E731
    out = (idx(y0, x0) * (wy0 * wx0) + idx(y0, x1) * (wy0 * wx1)
           + idx(y1, x0) * (wy1 * wx0) + idx(y1, x1) * (wy1 * wx1))
    valid = (ys > -1.0) & (ys < H) & (xs > -1.0) & (xs < W)
    return out * valid


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (parity: paddle.vision.ops.roi_align). boxes [R,4] xyxy in
    input-image coords, boxes_num [N] rois per batch element."""
    feat = _val(x)
    rois = _val(boxes).astype(jnp.float32)
    nums = np.asarray(_val(boxes_num))
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = np.repeat(np.arange(len(nums)), nums)
    offset = 0.5 if aligned else 0.0
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def one_roi(b_idx, roi):
        fmap = feat[b_idx]
        x1, y1, x2, y2 = roi * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        iy = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr
        gy = y1 + (jnp.arange(ph, dtype=jnp.float32)[:, None] * bin_h
                   + iy[None, :] * bin_h)
        gx = x1 + (jnp.arange(pw, dtype=jnp.float32)[:, None] * bin_w
                   + iy[None, :] * bin_w)
        ys = jnp.transpose(jnp.broadcast_to(
            gy[:, :, None, None], (ph, sr, pw, sr)), (0, 2, 1, 3))
        xs = jnp.broadcast_to(gx[None, :, None, :], (ph, pw, sr, sr))
        samples = _bilinear_sample(fmap, ys.reshape(-1), xs.reshape(-1))
        C = fmap.shape[0]
        return samples.reshape(C, ph, pw, sr * sr).mean(-1)

    outs = [one_roi(int(b), rois[i]) for i, b in enumerate(batch_idx)]
    if not outs:
        return Tensor(np.zeros((0, feat.shape[1], ph, pw), np.float32))
    return Tensor._from_value(jnp.stack(outs))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """RoI max pooling (parity: paddle.vision.ops.roi_pool)."""
    feat = np.asarray(_val(x))
    rois = np.asarray(_val(boxes), np.float32)
    nums = np.asarray(_val(boxes_num))
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = np.repeat(np.arange(len(nums)), nums)
    N, C, H, W = feat.shape
    out = np.zeros((rois.shape[0], C, ph, pw), feat.dtype)
    for i, b in enumerate(batch_idx):
        x1, y1, x2, y2 = np.round(rois[i] * spatial_scale).astype(np.int64)
        x2 = max(x2 + 1, x1 + 1)
        y2 = max(y2 + 1, y1 + 1)
        bin_h = (y2 - y1) / ph
        bin_w = (x2 - x1) / pw
        for py in range(ph):
            for px in range(pw):
                ys = int(np.floor(y1 + py * bin_h))
                ye = int(np.ceil(y1 + (py + 1) * bin_h))
                xs = int(np.floor(x1 + px * bin_w))
                xe = int(np.ceil(x1 + (px + 1) * bin_w))
                ys, ye = np.clip([ys, ye], 0, H)
                xs, xe = np.clip([xs, xe], 0, W)
                patch = feat[b, :, ys:ye, xs:xe]
                if patch.size:
                    out[i, :, py, px] = patch.max(axis=(1, 2))
    return Tensor(out)


# -- reference vision.ops surface (round-4 sweep): the detection ops live
# in .detection; re-exported here under the reference names, plus layer
# wrappers and the image-file ops --------------------------------------------
from .detection import (box_coder, prior_box, yolo_box, yolo_loss,  # noqa
                        matrix_nms, generate_proposals,
                        distribute_fpn_proposals, psroi_pool,
                        deformable_conv)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Parity: paddle.vision.ops.deform_conv2d (v2 when mask given)."""
    out = deformable_conv(x, offset, weight, mask=mask, stride=stride,
                          padding=padding, dilation=dilation,
                          deformable_groups=deformable_groups,
                          groups=groups)
    if bias is not None:
        out = out + bias.reshape([1, -1, 1, 1])
    return out


def read_file(filename, name=None):
    """Parity: paddle.vision.ops.read_file — raw bytes as a uint8
    tensor."""
    import numpy as np
    from ..core.tensor import Tensor
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(np.frombuffer(data, np.uint8).copy())


def decode_jpeg(x, mode="unchanged", name=None):
    """Parity: paddle.vision.ops.decode_jpeg — decode a uint8 byte
    tensor to CHW uint8 (PIL-backed host op; the reference uses
    nvjpeg)."""
    import io as _io
    import numpy as np
    from PIL import Image
    from ..core.tensor import Tensor
    raw = bytes(np.asarray(x._value if hasattr(x, "_value") else x,
                           np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr.copy())


class RoIAlign:
    """Parity: paddle.vision.ops.RoIAlign (layer wrapper)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool:
    """Parity: paddle.vision.ops.RoIPool."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool:
    """Parity: paddle.vision.ops.PSRoIPool."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


class DeformConv2D:
    """Parity: paddle.vision.ops.DeformConv2D (layer with weights)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        import numpy as np
        from ..nn.layer_base import Layer
        from ..nn import initializer as I
        helper = Layer.__new__(Layer)
        Layer.__init__(helper)
        k = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size, kernel_size)
        self._stride, self._padding = stride, padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self._helper = helper
        self.weight = helper.create_parameter(
            [out_channels, in_channels // groups, k[0], k[1]],
            attr=weight_attr, default_initializer=I.XavierUniform())
        if bias_attr is not False:
            self.bias = helper.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def parameters(self):
        return self._helper.parameters()

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)
