"""Vision datasets (parity: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100, DatasetFolder, ImageFolder, Flowers shim).

This environment has no network egress, so ``download=True`` requires the
files to already exist at ``image_path``/``data_file``; otherwise a clear
error explains what to place where. File formats match the originals
(idx-gzip for MNIST, python-pickle tar.gz for CIFAR) so real datasets
drop in unchanged.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, List, Optional

import numpy as np
from PIL import Image

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "Flowers", "VOC2012"]


def _require(path, what):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            "%s not found at %r. This environment has no network access: "
            "place the original dataset file there (same format as the "
            "reference's download)." % (what, path))
    return path


class MNIST(Dataset):
    """MNIST over idx-gzip files (parity: python/paddle/vision/datasets/mnist.py)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="pil"):
        assert mode.lower() in ("train", "test"), (
            "mode should be 'train' or 'test', but got %s" % mode)
        if backend not in ("pil", "cv2"):
            raise ValueError("backend should be 'pil' or 'cv2'")
        self.mode = mode.lower()
        self.backend = backend
        base = os.path.join(os.path.expanduser("~"), ".cache", "paddle",
                            "dataset", self.NAME)
        split = "train" if self.mode == "train" else "t10k"
        self.image_path = image_path or os.path.join(
            base, "%s-images-idx3-ubyte.gz" % split)
        self.label_path = label_path or os.path.join(
            base, "%s-labels-idx1-ubyte.gz" % split)
        _require(self.image_path, "MNIST images")
        _require(self.label_path, "MNIST labels")
        self.transform = transform
        self._parse()

    def _parse(self):
        with gzip.open(self.image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, "bad idx3 magic in %s" % self.image_path
            self.images = np.frombuffer(f.read(n * rows * cols),
                                        np.uint8).reshape(n, rows, cols)
        with gzip.open(self.label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, "bad idx1 magic in %s" % self.label_path
            self.labels = np.frombuffer(f.read(n), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.backend == "pil":
            img = Image.fromarray(img, mode="L")
        else:
            img = img[:, :, None]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], np.int64)

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 from the python-pickle tar.gz
    (parity: python/paddle/vision/datasets/cifar.py)."""

    _train_members = ["data_batch_%d" % i for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="pil"):
        assert mode.lower() in ("train", "test"), (
            "mode should be 'train' or 'test', but got %s" % mode)
        self.mode = mode.lower()
        self.backend = backend
        base = os.path.join(os.path.expanduser("~"), ".cache", "paddle",
                            "dataset", "cifar")
        self.data_file = data_file or os.path.join(
            base, "cifar-10-python.tar.gz" if self._label_key == b"labels"
            else "cifar-100-python.tar.gz")
        _require(self.data_file, "CIFAR archive")
        self.transform = transform
        self._load()

    def _load(self):
        names = (self._train_members if self.mode == "train"
                 else self._test_members)
        datas, labels = [], []
        with tarfile.open(self.data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in names:
                    batch = pickle.load(tf.extractfile(member),
                                        encoding="bytes")
                    datas.append(batch[b"data"])
                    labels.extend(batch[self._label_key])
        if not datas:
            raise RuntimeError("no %s members found in %s"
                               % (names, self.data_file))
        self.data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = np.transpose(self.data[idx], (1, 2, 0))
        label = self.labels[idx]
        if self.backend == "pil":
            img = Image.fromarray(img)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _default_loader(path):
    with open(path, "rb") as f:
        img = Image.open(f)
        return img.convert("RGB")


def _has_valid_extension(filename, extensions):
    return filename.lower().endswith(tuple(extensions))


class DatasetFolder(Dataset):
    """class-per-subdirectory layout (parity:
    python/paddle/vision/datasets/folder.py)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        classes, class_to_idx = self._find_classes(root)
        if is_valid_file is None:
            def is_valid_file(p):
                return _has_valid_extension(p, extensions)
        samples = []
        for target in sorted(class_to_idx.keys()):
            d = os.path.join(root, target)
            for r, _, fnames in sorted(os.walk(d)):
                for fname in sorted(fnames):
                    path = os.path.join(r, fname)
                    if is_valid_file(path):
                        samples.append((path, class_to_idx[target]))
        if not samples:
            raise RuntimeError("Found 0 files in subfolders of: %s" % root)
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]

    @staticmethod
    def _find_classes(dir):
        classes = sorted(d.name for d in os.scandir(dir) if d.is_dir())
        return classes, {c: i for i, c in enumerate(classes)}

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat/recursive image folder, samples only (parity: folder.py)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(p):
                return _has_valid_extension(p, extensions)
        samples = []
        for r, _, fnames in sorted(os.walk(root)):
            for fname in sorted(fnames):
                path = os.path.join(r, fname)
                if is_valid_file(path):
                    samples.append(path)
        if not samples:
            raise RuntimeError("Found 0 files in: %s" % root)
        self.samples = samples

    def __getitem__(self, index):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers102 (parity: python/paddle/vision/datasets/flowers.py:41 —
    102flowers tgz + imagelabels.mat + setid.mat; ``download=True`` is
    unsupported here, pass the files)."""

    _MODE_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        import tarfile
        import scipy.io as sio

        assert mode.lower() in self._MODE_KEY, \
            f"mode must be one of {sorted(self._MODE_KEY)}"
        _require(data_file, "Flowers images tgz (102flowers.tgz)")
        _require(label_file, "Flowers imagelabels.mat")
        _require(setid_file, "Flowers setid.mat")
        self.transform = transform
        self._labels = sio.loadmat(label_file)["labels"].ravel()
        setid = sio.loadmat(setid_file)
        self._indexes = setid[self._MODE_KEY[mode.lower()]].ravel()
        self._data_file = data_file
        self._tar_cache = (None, None)   # (pid, handle): fork safety
        self._names = {os.path.basename(n): n
                       for n in self._get_tar().getnames()
                       if n.endswith(".jpg")}

    def _get_tar(self):
        # DataLoader workers fork: a shared TarFile/fd would interleave
        # seeks across processes, so each process opens its own handle
        import tarfile
        pid, tar = self._tar_cache
        if pid != os.getpid():
            tar = tarfile.open(self._data_file)
            self._tar_cache = (os.getpid(), tar)
        return tar

    def __getitem__(self, idx):
        flower_id = int(self._indexes[idx])
        name = "image_%05d.jpg" % flower_id
        f = self._get_tar().extractfile(self._names[name])
        img = np.asarray(Image.open(f))
        label = np.array([self._labels[flower_id - 1]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self._indexes)


class VOC2012(Dataset):
    """VOC2012 segmentation (parity:
    python/paddle/vision/datasets/voc2012.py — VOCtrainval tar; yields
    (image, segmentation mask))."""

    _SPLIT_DIR = "ImageSets/Segmentation"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        import tarfile

        assert mode.lower() in ("train", "valid", "test"), mode
        _require(data_file, "VOC2012 tar (VOCtrainval_11-May-2012.tar)")
        self.transform = transform
        self._data_file = data_file
        self._tar_cache = (None, None)
        names = self._get_tar().getnames()
        split_name = {"train": "train.txt", "valid": "val.txt",
                      "test": "val.txt"}[mode.lower()]
        split_path = next(n for n in names
                          if n.endswith(f"{self._SPLIT_DIR}/{split_name}"))
        ids = self._get_tar().extractfile(split_path).read().decode() \
            .split()
        self._jpeg = {os.path.basename(n)[:-4]: n for n in names
                      if "/JPEGImages/" in n and n.endswith(".jpg")}
        self._mask = {os.path.basename(n)[:-4]: n for n in names
                      if "/SegmentationClass/" in n
                      and n.endswith(".png")}
        self._ids = [i for i in ids if i in self._jpeg and i in self._mask]

    def _get_tar(self):
        import tarfile
        pid, tar = self._tar_cache
        if pid != os.getpid():
            tar = tarfile.open(self._data_file)
            self._tar_cache = (os.getpid(), tar)
        return tar

    def __getitem__(self, idx):
        key = self._ids[idx]
        tar = self._get_tar()
        img = np.asarray(Image.open(tar.extractfile(self._jpeg[key])))
        mask = np.asarray(Image.open(tar.extractfile(self._mask[key])))
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self._ids)
