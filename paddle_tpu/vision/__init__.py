"""paddle.vision (parity: python/paddle/vision/)."""
from . import datasets, models, ops, transforms

_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            "Expected backend are one of ['pil', 'cv2', 'tensor'], but got "
            "{}".format(backend))
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (PIL backend; cv2 not bundled)."""
    backend = backend or _image_backend
    from PIL import Image
    import numpy as np
    img = Image.open(path)
    if backend == "pil":
        return img
    arr = np.asarray(img)
    if backend == "cv2":
        return arr[..., ::-1] if arr.ndim == 3 else arr   # RGB->BGR
    from ..core.tensor import Tensor
    return Tensor(arr)


__all__ = ["datasets", "models", "ops", "transforms", "set_image_backend",
           "get_image_backend", "image_load"]
