"""Audio feature layers (parity: python/paddle/audio/features/layers.py —
Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)."""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .. import signal as _signal
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = F.get_window(window, self.win_length)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length,
                            self.win_length, window=self.fft_window,
                            center=self.center, pad_mode=self.pad_mode)
        mag = jnp.abs(spec._value)
        if self.power != 1.0:
            mag = mag ** self.power
        return Tensor._from_value(mag)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode)
        self.fbank_matrix = F.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm)

    def forward(self, x):
        spec = self._spectrogram(x)._value     # [..., freq, time]
        mel = jnp.matmul(self.fbank_matrix._value, spec)
        return Tensor._from_value(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 **kw):
        super().__init__()
        self._mel = MelSpectrogram(sr=sr, **kw)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return F.power_to_db(self._mel(x), self.ref_value, self.amin,
                             self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40,
                 norm: str = "ortho", **kw):
        super().__init__()
        self._log_mel = LogMelSpectrogram(sr=sr, **kw)
        n_mels = self._log_mel._mel.fbank_matrix.shape[0]
        self.dct_matrix = F.create_dct(n_mfcc, n_mels, norm)

    def forward(self, x):
        log_mel = self._log_mel(x)._value      # [..., n_mels, time]
        mfcc = jnp.einsum("mk,...mt->...kt", self.dct_matrix._value,
                          log_mel)
        return Tensor._from_value(mfcc)
