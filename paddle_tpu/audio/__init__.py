"""paddle.audio (parity: python/paddle/audio/ — functional/functional.py
mel/fbank/dct helpers, features/layers.py Spectrogram/MelSpectrogram/
LogMelSpectrogram/MFCC, window functions)."""
from . import functional, features
from .features import (Spectrogram, MelSpectrogram, LogMelSpectrogram,
                       MFCC)

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
