"""paddle.audio (parity: python/paddle/audio/ — functional/functional.py
mel/fbank/dct helpers, features/layers.py Spectrogram/MelSpectrogram/
LogMelSpectrogram/MFCC, window functions)."""
from . import functional, features
from . import backends
from . import datasets
from .backends.wave_backend import load, save, info
from .features import (Spectrogram, MelSpectrogram, LogMelSpectrogram,
                       MFCC)

__all__ = ["functional", "features", "datasets", "backends",
           "load", "info", "save",
           "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
