"""paddle.audio.datasets (parity: python/paddle/audio/datasets/ — ESC50,
TESS).  No network egress in this environment: pass ``archive_dir``
pointing at the extracted dataset (same directory layout the reference
downloads); feature modes (raw/spectrogram/melspectrogram/logmelspectrogram/
mfcc) match the reference's feature plumbing."""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ...io import Dataset
from ..backends.wave_backend import load as _load

__all__ = ["ESC50", "TESS"]


class _AudioClassificationDataset(Dataset):
    feat_types = ("raw", "spectrogram", "melspectrogram",
                  "logmelspectrogram", "mfcc")

    def __init__(self, files: List[str], labels: List[int],
                 feat_type: str = "raw", sample_rate: int = 16000,
                 **kwargs):
        if feat_type not in self.feat_types:
            raise RuntimeError(
                f"feat_type {feat_type!r} not in {self.feat_types}")
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self.feat_config = kwargs
        self.sample_rate = sample_rate

    def _convert_to_record(self, idx):
        waveform, sr = _load(self.files[idx], channels_first=False)
        wav = np.asarray(waveform._value)[:, 0]
        if self.feat_type == "raw":
            return wav.astype(np.float32), self.labels[idx]
        from .. import features as _feat
        from ...core.tensor import Tensor
        name = {"spectrogram": "Spectrogram",
                "melspectrogram": "MelSpectrogram",
                "logmelspectrogram": "LogMelSpectrogram",
                "mfcc": "MFCC"}[self.feat_type]
        extractor = getattr(_feat, name)(sr=sr, **self.feat_config)
        out = extractor(Tensor(wav[None, :]))
        return np.asarray(out._value)[0], self.labels[idx]

    def __getitem__(self, idx):
        return self._convert_to_record(idx)

    def __len__(self):
        return len(self.files)


class TESS(_AudioClassificationDataset):
    """Toronto Emotional Speech Set (parity: audio/datasets/tess.py).
    Layout: <archive_dir>/TESS_Toronto_emotional_speech_set_data/
    <speaker>_<word>_<emotion>.wav (any nesting); the emotion is the
    label, parsed from the filename like the reference."""

    n_folds = 5
    label_list = ["angry", "disgust", "fear", "happy", "neutral",
                  "ps", "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feat_type: str = "raw",
                 archive_dir: Optional[str] = None, **kwargs):
        if not 1 <= split <= n_folds:
            raise ValueError(f"split must be in [1, {n_folds}]")
        if archive_dir is None:
            raise RuntimeError(
                "no network egress: pass archive_dir=<path to the "
                "extracted TESS dataset>")
        wavs = []
        for root, _, files in os.walk(archive_dir):
            for fn in sorted(files):
                if fn.lower().endswith(".wav"):
                    wavs.append(os.path.join(root, fn))
        files, labels = [], []
        for i, path in enumerate(wavs):
            emotion = os.path.basename(path).rsplit(".", 1)[0] \
                .split("_")[-1].lower()
            if emotion not in self.label_list:
                continue
            fold = i % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                files.append(path)
                labels.append(self.label_list.index(emotion))
        super().__init__(files, labels, feat_type, **kwargs)


class ESC50(_AudioClassificationDataset):
    """ESC-50 environmental sounds (parity: audio/datasets/esc50.py).
    Layout: <archive_dir>/ESC-50-master/{meta/esc50.csv, audio/*.wav};
    fold-based train/dev split like the reference."""

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw",
                 archive_dir: Optional[str] = None, **kwargs):
        if archive_dir is None:
            raise RuntimeError(
                "no network egress: pass archive_dir=<path to the "
                "extracted ESC-50 dataset>")
        meta = None
        for cand in (os.path.join(archive_dir, "ESC-50-master", "meta",
                                  "esc50.csv"),
                     os.path.join(archive_dir, "meta", "esc50.csv")):
            if os.path.exists(cand):
                meta = cand
                break
        if meta is None:
            raise FileNotFoundError("esc50.csv not found under "
                                    f"{archive_dir}")
        audio_dir = os.path.join(os.path.dirname(os.path.dirname(meta)),
                                 "audio")
        files, labels = [], []
        with open(meta) as f:
            header = f.readline().strip().split(",")
            fi = header.index("filename")
            foldi = header.index("fold")
            ti = header.index("target")
            for line in f:
                parts = line.strip().split(",")
                fold = int(parts[foldi])
                keep = (fold != split) if mode == "train" \
                    else (fold == split)
                if keep:
                    files.append(os.path.join(audio_dir, parts[fi]))
                    labels.append(int(parts[ti]))
        super().__init__(files, labels, feat_type, **kwargs)
