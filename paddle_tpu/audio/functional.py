"""Audio functional ops (parity:
python/paddle/audio/functional/functional.py — hz_to_mel :24, mel_to_hz
:80, mel_frequencies :125, fft_frequencies :165, compute_fbank_matrix
:188, power_to_db :261, create_dct :305; window functions in window.py).
"""
from __future__ import annotations

import math
from typing import Optional, Union

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def _v(x):
    return x._value if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk: bool = False):
    """Parity: functional.py:24."""
    scalar = not isinstance(freq, (Tensor, jnp.ndarray, np.ndarray))
    f = jnp.asarray(_v(freq), jnp.float32)
    if htk:
        mel = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(
                            jnp.maximum(f, 1e-10) / min_log_hz) / logstep,
                        mel)
    return float(mel) if scalar else Tensor._from_value(mel)


def mel_to_hz(mel, htk: bool = False):
    """Parity: functional.py:80."""
    scalar = not isinstance(mel, (Tensor, jnp.ndarray, np.ndarray))
    m = jnp.asarray(_v(mel), jnp.float32)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = jnp.where(m >= min_log_mel,
                       min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                       hz)
    return float(hz) if scalar else Tensor._from_value(hz)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype: str = "float32"):
    """Parity: functional.py:125."""
    min_mel = hz_to_mel(f_min, htk)
    max_mel = hz_to_mel(f_max, htk)
    mels = jnp.linspace(min_mel, max_mel, n_mels)
    return mel_to_hz(Tensor._from_value(mels), htk)


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32") -> Tensor:
    """Parity: functional.py:165."""
    return Tensor._from_value(
        jnp.linspace(0, sr / 2.0, 1 + n_fft // 2))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: str = "slaney",
                         dtype: str = "float32") -> Tensor:
    """Triangular mel filterbank [n_mels, 1+n_fft//2]
    (parity: functional.py:188)."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)._value
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)._value
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor._from_value(weights)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """Parity: functional.py:261."""
    s = jnp.asarray(_v(spect), jnp.float32)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor._from_value(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype: str = "float32") -> Tensor:
    """DCT-II matrix [n_mels, n_mfcc] (parity: functional.py:305)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k) * 2.0
    if norm is None:
        dct = dct / 2.0
    else:
        assert norm == "ortho"
        dct = dct.at[:, 0].multiply(math.sqrt(1.0 / (4 * n_mels)))
        dct = dct.at[:, 1:].multiply(math.sqrt(1.0 / (2 * n_mels)))
    return Tensor._from_value(dct)


def get_window(window: Union[str, tuple], win_length: int,
               fftbins: bool = True, dtype: str = "float32") -> Tensor:
    """Parity: window.py get_window (hann/hamming/blackman/kaiser/
    taylor subset over scipy)."""
    import scipy.signal as ss
    w = ss.get_window(window, win_length, fftbins=fftbins)
    return Tensor(np.asarray(w, np.float32))
