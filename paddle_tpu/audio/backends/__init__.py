"""paddle.audio.backends (parity: python/paddle/audio/backends/
init_backend.py — get_current_backend / list_available_backends /
set_backend).  The in-tree wave backend is always available; soundfile
registers if its wheel is importable (it is not baked into this
environment)."""
from . import wave_backend
from .wave_backend import info, load, save, AudioInfo

__all__ = ["get_current_backend", "list_available_backends",
           "set_backend"]

_BACKEND = ["wave_backend"]


def list_available_backends():
    """Parity: init_backend.list_available_backends."""
    backends = ["wave_backend"]
    try:
        import soundfile  # noqa: F401
        backends.append("soundfile")
    except ImportError:
        pass
    return backends


def get_current_backend() -> str:
    """Parity: init_backend.get_current_backend."""
    return _BACKEND[0]


def set_backend(backend_name: str):
    """Parity: init_backend.set_backend."""
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name!r} is not available "
            f"(available: {list_available_backends()})")
    _BACKEND[0] = backend_name
