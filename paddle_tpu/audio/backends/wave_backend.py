"""In-tree WAV codec backend (parity: python/paddle/audio/backends/
wave_backend.py — the reference's default backend is also built on the
stdlib ``wave`` module, PCM16)."""
from __future__ import annotations

import wave
from typing import Optional, Tuple

import numpy as np

from ...core.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save"]


class AudioInfo:
    """Parity: backends/backend.AudioInfo."""

    def __init__(self, sample_rate, num_frames, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_frames={self.num_frames}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample}, "
                f"encoding={self.encoding!r})")


def info(filepath: str) -> AudioInfo:
    """Parity: paddle.audio.info."""
    with wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(),
                         f.getnchannels(), f.getsampwidth() * 8,
                         "PCM_S")


def load(filepath, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True
         ) -> Tuple[Tensor, int]:
    """Parity: paddle.audio.load — PCM16 WAV; normalize=True returns
    float32 in (-1, 1), else raw int16-valued float32."""
    file_obj = filepath if hasattr(filepath, "read") \
        else open(filepath, "rb")
    try:
        f = wave.open(file_obj)
    except wave.Error:
        file_obj.seek(0)
        file_obj.close()
        raise NotImplementedError(
            "wave backend supports PCM16 WAV files only")
    channels = f.getnchannels()
    sample_rate = f.getframerate()
    frames = f.getnframes()
    width = f.getsampwidth()
    content = f.readframes(frames)
    file_obj.close()
    if width != 2:
        raise NotImplementedError(
            f"wave backend reads PCM16 (2-byte) samples; file has "
            f"{width}-byte samples")
    audio = np.frombuffer(content, dtype=np.int16).astype(np.float32)
    if normalize:
        audio = audio / (2 ** 15)
    waveform = audio.reshape(frames, channels)
    if num_frames != -1:
        waveform = waveform[frame_offset:frame_offset + num_frames]
    elif frame_offset:
        waveform = waveform[frame_offset:]
    if channels_first:
        waveform = waveform.T
    return Tensor(np.ascontiguousarray(waveform)), sample_rate


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True, encoding: Optional[str] = None,
         bits_per_sample: Optional[int] = 16):
    """Parity: paddle.audio.save — PCM16 WAV."""
    arr = np.asarray(src._value if isinstance(src, Tensor) else src)
    if arr.ndim != 2:
        raise AssertionError("Expected 2D tensor")
    if bits_per_sample not in (None, 16) or encoding not in (None,
                                                             "PCM_S"):
        raise ValueError("wave backend saves PCM16 only")
    if channels_first:
        arr = arr.T                      # -> (time, channels)
    if np.issubdtype(arr.dtype, np.floating):
        arr = np.clip(arr, -1.0, 1.0 - 1.0 / (2 ** 15))
        arr = (arr * (2 ** 15)).astype(np.int16)
    else:
        arr = arr.astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(arr).tobytes())
