"""Top-level inplace op surface (paddle.<op>_ functions).

Parity: the reference's generated inplace API (python/paddle/tensor/
__init__.py exports cumsum_/equal_/where_/... backed by inplace C++
kernels).  TPU-native: jax.Arrays are immutable, so "inplace" is
compute-then-rebind on the Tensor (``_inplace_assign`` keeps tape
continuity), exactly like the Tensor-method variants the registry
already generates."""
from __future__ import annotations

from typing import Callable, Dict

from ..core.tensor import Tensor
from . import registry

# ops the reference exposes with a top-level trailing-underscore variant
_INPLACE_NAMES = [
    "abs", "acos", "acosh", "addmm", "asin", "asinh", "atan", "atanh",
    "ceil", "clip", "cos", "cosh", "cumprod", "cumsum", "digamma",
    "divide", "equal", "erf", "erfinv", "exp", "expm1", "fill_diagonal",
    "flatten", "floor", "floor_divide", "frac", "gammaln", "gcd",
    "greater_equal", "greater_than", "hypot", "i0", "index_add",
    "index_put", "lcm", "ldexp", "lerp", "less_equal", "less_than",
    "lgamma", "log", "log10", "log1p", "log2", "logical_and",
    "logical_not", "logical_or", "logical_xor", "logit", "masked_fill",
    "multiply", "nan_to_num", "neg", "not_equal", "pow", "put_along_axis",
    "reciprocal", "remainder", "renorm", "round", "rsqrt", "scatter",
    "sigmoid", "sin", "sinh", "sqrt", "square", "squeeze", "subtract",
    "t", "tan", "tanh", "tril", "triu", "trunc", "unsqueeze", "where",
    "floor_mod", "mod", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not", "cast", "transpose", "reshape", "polygamma",
    "copysign", "bitwise_left_shift", "bitwise_right_shift",
    "masked_scatter",
]


def _make(fn: Callable) -> Callable:
    def inplace(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        return x._inplace_assign(out)

    inplace.__doc__ = (f"In-place variant of {fn.__name__} "
                       "(compute + rebind; tape continuity preserved).")
    inplace.__name__ = fn.__name__ + "_"
    return inplace


def build() -> Dict[str, Callable]:
    ops = registry.registered_ops()
    out = {}
    for name in _INPLACE_NAMES:
        opdef = ops.get(name)
        if opdef is not None:
            out[name + "_"] = _make(opdef.fn)
    return out
