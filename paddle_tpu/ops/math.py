"""Elementwise math ops.

Parity: reference kernels in paddle/phi/kernels/ (activation_kernel.cc,
elementwise_*_kernel.cc), Python surface python/paddle/tensor/math.py and
python/paddle/tensor/ops.py.  Every op is a pure function over jax arrays,
lowered/fused by XLA — on TPU these fuse into neighboring matmuls instead of
being standalone CUDA kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy import special as jspecial

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .registry import register, register_op
from ._helpers import def_unary, def_binary, as_value, unwrap, wrap, targ

# ---------------------------------------------------------------------------
# unary table
# ---------------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs,
    "acos": jnp.arccos,
    "asin": jnp.arcsin,
    "atan": jnp.arctan,
    "acosh": jnp.arccosh,
    "asinh": jnp.arcsinh,
    "atanh": jnp.arctanh,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "cos": jnp.cos,
    "cosh": jnp.cosh,
    "sin": jnp.sin,
    "sinh": jnp.sinh,
    "tan": jnp.tan,
    "tanh": jnp.tanh,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lax.rsqrt,
    "square": jnp.square,
    "reciprocal": jnp.reciprocal,
    "sign": jnp.sign,
    "erf": jax.scipy.special.erf,
    "erfinv": jspecial.erfinv,
    "sigmoid": jax.nn.sigmoid,
    "lgamma": jspecial.gammaln,
    "digamma": jspecial.digamma,
    "i0": lambda x: jspecial.i0(x),
    "i0e": lambda x: jspecial.i0e(x),
    "i1": lambda x: jspecial.i1(x),
    "i1e": lambda x: jspecial.i1e(x),
    "angle": jnp.angle,
    "conj": jnp.conj,
    "trunc": jnp.trunc,
    "frac": lambda x: x - jnp.trunc(x),
    "neg": jnp.negative,
    "deg2rad": jnp.deg2rad,
    "rad2deg": jnp.rad2deg,
}
for _n, _f in _UNARY.items():
    globals()[_n] = def_unary(_n, _f)

# non-differentiable predicates (no tape: bool outputs)
_UNARY_PRED = {
    "isnan": jnp.isnan,
    "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
    "isneginf": jnp.isneginf,
    "isposinf": jnp.isposinf,
    "isreal": jnp.isreal,
    "signbit": jnp.signbit,
}
for _n, _f in _UNARY_PRED.items():
    globals()[_n] = def_unary(_n, _f, category="logic", inplace=False)


@register_op("round", category="math", tensor_method=True, inplace_alias=True)
def round(x, decimals=0, name=None):
    return apply_op("round", lambda v: jnp.round(v, decimals), (x,))


# ---------------------------------------------------------------------------
# binary table
# ---------------------------------------------------------------------------
_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "floor_divide": jnp.floor_divide,
    "mod": jnp.mod,
    "remainder": jnp.remainder,
    "floor_mod": jnp.mod,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "fmax": jnp.fmax,
    "fmin": jnp.fmin,
    "atan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp,
    "hypot": jnp.hypot,
    "copysign": jnp.copysign,
    "nextafter": jnp.nextafter,
    "heaviside": jnp.heaviside,
    "gcd": jnp.gcd,
    "lcm": jnp.lcm,
    "ldexp": jnp.ldexp,
    "polygamma": lambda x, n: jspecial.polygamma(n, x),
}
for _n, _f in _BINARY.items():
    globals()[_n] = def_binary(_n, _f)

_BINARY_PRED = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater_than": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "less_than": jnp.less,
    "less_equal": jnp.less_equal,
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "bitwise_and": jnp.bitwise_and,
    "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
    "bitwise_left_shift": jnp.left_shift,
    "bitwise_right_shift": jnp.right_shift,
}
for _n, _f in _BINARY_PRED.items():
    globals()[_n] = def_binary(_n, _f, category="logic", inplace=False)

globals()["logical_not"] = def_unary("logical_not", jnp.logical_not,
                                     category="logic", inplace=False)
globals()["bitwise_not"] = def_unary("bitwise_not", jnp.bitwise_not,
                                     category="logic", inplace=False)


@register_op("pow", category="math", tensor_method=True, inplace_alias=True)
def pow(x, y, name=None):
    return apply_op("pow", jnp.power, (x, targ(y)))


@register_op("scale", category="math", tensor_method=True, inplace_alias=True)
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """Parity: paddle.scale (phi scale kernel)."""
    def fn(v, s, b):
        out = v * s + b if bias_after_scale else (v + b) * s
        return out
    return apply_op("scale", fn, (x, targ(scale), targ(bias)))


@register_op("clip", category="math", tensor_method=True, inplace_alias=True)
def clip(x, min=None, max=None, name=None):
    def fn(v, lo, hi):
        return jnp.clip(v, lo, hi)
    lo = as_value(min) if min is not None else None
    hi = as_value(max) if max is not None else None
    return apply_op("clip", lambda v: jnp.clip(v, lo, hi), (x,))


clamp = clip
register("clamp", clip, category="math", tensor_method=True,
         method_name="clamp")


@register_op("stanh", category="math", tensor_method=True)
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op("stanh",
                    lambda v: scale_b * jnp.tanh(scale_a * v), (x,))


@register_op("multiplex", category="math")
def multiplex(inputs, index, name=None):
    def fn(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))),
            axis=0)[0]
    return apply_op("multiplex", fn, (index.flatten(), *inputs))


@register_op("add_n", category="math")
def add_n(inputs, name=None):
    """Parity: paddle.add_n (sum_op)."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    def fn(*xs):
        out = xs[0]
        for v in xs[1:]:
            out = out + v
        return out
    return apply_op("add_n", fn, tuple(inputs))


@register_op("nan_to_num", category="math", tensor_method=True,
             inplace_alias=True)
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op("nan_to_num",
                    lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                             neginf=neginf), (x,))


@register_op("lerp", category="math", tensor_method=True, inplace_alias=True)
def lerp(x, y, weight, name=None):
    return apply_op("lerp", lambda a, b, w: a + w * (b - a),
                    (x, targ(y), targ(weight)))


@register_op("logit", category="math", tensor_method=True)
def logit(x, eps=None, name=None):
    def fn(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v / (1.0 - v))
    return apply_op("logit", fn, (x,))


@register_op("log_normalize", category="math")
def log_normalize(x, axis=-1, name=None):
    return apply_op("log_normalize",
                    lambda v: v - jspecial.logsumexp(v, axis=axis,
                                                     keepdims=True), (x,))


@register_op("real", category="math", tensor_method=True)
def real(x, name=None):
    return apply_op("real", jnp.real, (x,))


@register_op("imag", category="math", tensor_method=True)
def imag(x, name=None):
    return apply_op("imag", jnp.imag, (x,))


@register_op("diff", category="math", tensor_method=True)
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [x]
    if prepend is not None:
        args.append(prepend)
    if append is not None:
        args.append(append)

    def fn(v, *extra):
        i = 0
        pre = post = None
        if prepend is not None:
            pre = extra[i]; i += 1
        if append is not None:
            post = extra[i]
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=post)
    return apply_op("diff", fn, tuple(args))


@register_op("trapezoid", category="math")
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply_op("trapezoid",
                        lambda yy, xx: jax.scipy.integrate.trapezoid(
                            yy, xx, axis=axis), (y, targ(x)))
    d = 1.0 if dx is None else dx
    return apply_op("trapezoid",
                    lambda yy: jax.scipy.integrate.trapezoid(
                        yy, dx=d, axis=axis), (y,))
