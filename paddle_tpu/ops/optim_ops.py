"""Functional optimizer-update ops.

Parity: the reference's optimizer op family in
paddle/phi/api/yaml/ops.yaml (sgd_, momentum_, adam_, adamw_, lamb_,
adagrad_, adadelta_, adamax_, rmsprop_, rprop_, merged_/fused_ variants,
average_accumulates_, plus the AMP bookkeeping ops
check_finite_and_unscale_ / update_loss_scaling_).  The optimizer
*classes* (paddle_tpu/optimizer/) are the stateful API; these are the
op-level single-step update rules operating on explicit state tensors —
in-place on the param/state (trailing-underscore semantics), returning
the updated tensors.  Each is one fused XLA computation.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op
from ._helpers import as_value, wrap, targ


def _assign(t, new_val):
    """In-place update honoring trailing-underscore op semantics."""
    if isinstance(t, Tensor):
        t._inplace_assign(wrap(new_val))
        return t
    return wrap(new_val)


def _f32(v):
    return as_value(v).astype(jnp.float32)


def _skip(skip_update) -> bool:
    """AMP overflow skip: when skip_update is truthy the reference op
    leaves params AND optimizer state untouched."""
    if skip_update is None:
        return False
    return bool(np.asarray(as_value(skip_update)))


def sgd_(param, learning_rate, grad, master_param=None,
         multi_precision=False, name=None):
    """Parity: reference sgd_ op."""
    lr = _f32(learning_rate)
    acc = _f32(master_param) if master_param is not None else _f32(param)
    new = acc - lr * _f32(grad)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def momentum_(param, grad, velocity, learning_rate, master_param=None,
              mu=0.9, use_nesterov=False, regularization_method="",
              regularization_coeff=0.0, multi_precision=False,
              rescale_grad=1.0, name=None):
    """Parity: reference momentum_ op."""
    lr = _f32(learning_rate)
    g = _f32(grad) * rescale_grad
    p = _f32(master_param) if master_param is not None else _f32(param)
    if regularization_method == "l2_decay":
        g = g + regularization_coeff * p
    v = mu * _f32(velocity) + g
    if use_nesterov:
        new = p - lr * (g + mu * v)
    else:
        new = p - lr * v
    _assign(velocity, v)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def adam_(param, grad, learning_rate, moment1, moment2, beta1_pow,
          beta2_pow, master_param=None, skip_update=None, beta1=0.9,
          beta2=0.999, epsilon=1e-8, lazy_mode=False,
          min_row_size_to_use_multithread=1000, multi_precision=False,
          use_global_beta_pow=False, name=None):
    """Parity: reference adam_ op."""
    if _skip(skip_update):
        return param
    lr = _f32(learning_rate)
    g = _f32(grad)
    p = _f32(master_param) if master_param is not None else _f32(param)
    m1 = beta1 * _f32(moment1) + (1 - beta1) * g
    m2 = beta2 * _f32(moment2) + (1 - beta2) * g * g
    b1p = _f32(beta1_pow) * beta1
    b2p = _f32(beta2_pow) * beta2
    mhat = m1 / (1 - b1p)
    vhat = m2 / (1 - b2p)
    new = p - lr * mhat / (jnp.sqrt(vhat) + epsilon)
    _assign(moment1, m1)
    _assign(moment2, m2)
    _assign(beta1_pow, b1p)
    _assign(beta2_pow, b2p)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def adamw_(param, grad, learning_rate, moment1, moment2, beta1_pow,
           beta2_pow, master_param=None, skip_update=None, beta1=0.9,
           beta2=0.999, epsilon=1e-8, lr_ratio=1.0, coeff=0.01,
           with_decay=True, lazy_mode=False,
           min_row_size_to_use_multithread=1000, multi_precision=False,
           use_global_beta_pow=False, name=None):
    """Parity: reference adamw_ op (decoupled weight decay)."""
    if _skip(skip_update):
        return param
    lr = _f32(learning_rate) * lr_ratio
    p = _f32(master_param) if master_param is not None else _f32(param)
    if with_decay:
        p = p * (1.0 - lr * coeff)
    g = _f32(grad)
    m1 = beta1 * _f32(moment1) + (1 - beta1) * g
    m2 = beta2 * _f32(moment2) + (1 - beta2) * g * g
    b1p = _f32(beta1_pow) * beta1
    b2p = _f32(beta2_pow) * beta2
    new = p - lr * (m1 / (1 - b1p)) / (
        jnp.sqrt(m2 / (1 - b2p)) + epsilon)
    _assign(moment1, m1)
    _assign(moment2, m2)
    _assign(beta1_pow, b1p)
    _assign(beta2_pow, b2p)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def adagrad_(param, grad, moment, learning_rate, master_param=None,
             epsilon=1e-6, multi_precision=False, name=None):
    """Parity: reference adagrad_ op."""
    g = _f32(grad)
    mom = _f32(moment) + g * g
    p = _f32(master_param) if master_param is not None else _f32(param)
    new = p - _f32(learning_rate) * g / (jnp.sqrt(mom) + epsilon)
    _assign(moment, mom)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def adadelta_(param, grad, avg_squared_grad, avg_squared_update,
              learning_rate=None, master_param=None, rho=0.95,
              epsilon=1e-6, multi_precision=False, name=None):
    """Parity: reference adadelta_ op."""
    g = _f32(grad)
    asg = rho * _f32(avg_squared_grad) + (1 - rho) * g * g
    upd = g * jnp.sqrt(_f32(avg_squared_update) + epsilon) / \
        jnp.sqrt(asg + epsilon)
    asu = rho * _f32(avg_squared_update) + (1 - rho) * upd * upd
    lr = _f32(learning_rate) if learning_rate is not None else 1.0
    p = _f32(master_param) if master_param is not None else _f32(param)
    new = p - lr * upd
    _assign(avg_squared_grad, asg)
    _assign(avg_squared_update, asu)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def adamax_(param, grad, learning_rate, moment, inf_norm, beta1_pow,
            master_param=None, beta1=0.9, beta2=0.999, epsilon=1e-8,
            multi_precision=False, name=None):
    """Parity: reference adamax_ op."""
    g = _f32(grad)
    m = beta1 * _f32(moment) + (1 - beta1) * g
    inf = jnp.maximum(beta2 * _f32(inf_norm), jnp.abs(g) + epsilon)
    lr = _f32(learning_rate) / (1 - _f32(beta1_pow))
    p = _f32(master_param) if master_param is not None else _f32(param)
    new = p - lr * m / inf
    _assign(moment, m)
    _assign(inf_norm, inf)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def rmsprop_(param, mean_square, grad, moment, learning_rate,
             mean_grad=None, master_param=None, epsilon=1e-10,
             decay=0.9, momentum=0.0, centered=False,
             multi_precision=False, name=None):
    """Parity: reference rmsprop_ op."""
    g = _f32(grad)
    ms = decay * _f32(mean_square) + (1 - decay) * g * g
    if centered and mean_grad is not None:
        mg = decay * _f32(mean_grad) + (1 - decay) * g
        denom = jnp.sqrt(ms - mg * mg + epsilon)
        _assign(mean_grad, mg)
    else:
        denom = jnp.sqrt(ms + epsilon)
    mom = momentum * _f32(moment) + _f32(learning_rate) * g / denom
    p = _f32(master_param) if master_param is not None else _f32(param)
    new = p - mom
    _assign(mean_square, ms)
    _assign(moment, mom)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def rprop_(param, grad, prev, learning_rate, master_param=None,
           learning_rate_range=(1e-5, 50.0), etas=(0.5, 1.2),
           multi_precision=False, name=None):
    """Parity: reference rprop_ op (sign-based step adaptation)."""
    g = _f32(grad)
    pg = _f32(prev)
    lr = _f32(learning_rate)
    sign = jnp.sign(g * pg)
    eta_n, eta_p = etas
    lo, hi = learning_rate_range
    lr = jnp.clip(jnp.where(sign > 0, lr * eta_p,
                            jnp.where(sign < 0, lr * eta_n, lr)),
                  lo, hi)
    g_eff = jnp.where(sign < 0, 0.0, g)
    p = _f32(master_param) if master_param is not None else _f32(param)
    new = p - lr * jnp.sign(g_eff)
    _assign(prev, g_eff)
    _assign(learning_rate, lr)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def lamb_(param, grad, learning_rate, moment1, moment2, beta1_pow,
          beta2_pow, master_param=None, skip_update=None,
          weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6,
          always_adapt=False, multi_precision=False, name=None):
    """Parity: reference lamb_ op (layerwise trust-ratio Adam)."""
    if _skip(skip_update):
        return param
    g = _f32(grad)
    p = _f32(master_param) if master_param is not None else _f32(param)
    m1 = beta1 * _f32(moment1) + (1 - beta1) * g
    m2 = beta2 * _f32(moment2) + (1 - beta2) * g * g
    b1p = _f32(beta1_pow) * beta1
    b2p = _f32(beta2_pow) * beta2
    upd = (m1 / (1 - b1p)) / (jnp.sqrt(m2 / (1 - b2p)) + epsilon)
    upd = upd + weight_decay * p
    w_norm = jnp.linalg.norm(p)
    u_norm = jnp.linalg.norm(upd)
    ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                      w_norm / u_norm, 1.0)
    new = p - ratio * _f32(learning_rate) * upd
    _assign(moment1, m1)
    _assign(moment2, m2)
    _assign(beta1_pow, b1p)
    _assign(beta2_pow, b2p)
    if master_param is not None:
        _assign(master_param, new)
    return _assign(param, new.astype(as_value(param).dtype))


def merged_adam_(params, grads, learning_rate, moments1, moments2,
                 beta1_pows, beta2_pows, master_params=None, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, multi_precision=False,
                 use_global_beta_pow=False, name=None):
    """Parity: reference merged_adam_ op (multi-tensor apply)."""
    mp = master_params or [None] * len(params)
    for p, g, m1, m2, b1, b2, m in zip(params, grads, moments1,
                                       moments2, beta1_pows, beta2_pows,
                                       mp):
        adam_(p, g, learning_rate, m1, m2, b1, b2, master_param=m,
              beta1=beta1, beta2=beta2, epsilon=epsilon,
              multi_precision=multi_precision)
    return params


def merged_momentum_(params, grads, velocitys, learning_rate,
                     master_params=None, mu=0.9, use_nesterov=False,
                     regularization_method=None,
                     regularization_coeff=None, multi_precision=False,
                     rescale_grad=1.0, name=None):
    """Parity: reference merged_momentum_ op."""
    mp = master_params or [None] * len(params)
    for i, (p, g, v, m) in enumerate(zip(params, grads, velocitys, mp)):
        momentum_(p, g, v, learning_rate, master_param=m, mu=mu,
                  use_nesterov=use_nesterov,
                  regularization_method=(regularization_method[i]
                                         if regularization_method
                                         else ""),
                  regularization_coeff=(regularization_coeff[i]
                                        if regularization_coeff
                                        else 0.0),
                  multi_precision=multi_precision,
                  rescale_grad=rescale_grad)
    return params


def fused_adam_(params, grads, learning_rate, moments1, moments2,
                beta1_pows, beta2_pows, master_params=None,
                skip_update=None, beta1=0.9, beta2=0.999, epsilon=1e-8,
                chunk_size=32768, weight_decay=0.0, use_adamw=False,
                multi_precision=False, use_global_beta_pow=False,
                name=None):
    """Parity: reference fused_adam_ op — XLA fuses the whole multi-
    tensor update into one executable, the TPU analog of the chunked
    CUDA multi_tensor kernel."""
    if _skip(skip_update):
        return params
    mp = master_params or [None] * len(params)
    for p, g, m1, m2, b1, b2, m in zip(params, grads, moments1,
                                       moments2, beta1_pows, beta2_pows,
                                       mp):
        if use_adamw:
            adamw_(p, g, learning_rate, m1, m2, b1, b2, master_param=m,
                   beta1=beta1, beta2=beta2, epsilon=epsilon,
                   coeff=weight_decay,
                   multi_precision=multi_precision)
        else:
            adam_(p, g, learning_rate, m1, m2, b1, b2, master_param=m,
                  beta1=beta1, beta2=beta2, epsilon=epsilon,
                  multi_precision=multi_precision)
    return params


def average_accumulates_(param, in_sum_1, in_sum_2, in_sum_3,
                         in_num_accumulates, in_old_num_accumulates,
                         in_num_updates, average_window=10000,
                         max_average_window=10000,
                         min_average_window=10000, name=None):
    """Parity: reference average_accumulates_ op (ModelAverage's
    windowed parameter-sum bookkeeping)."""
    p = _f32(param)
    s1 = _f32(in_sum_1) + p
    num = as_value(in_num_accumulates).astype(jnp.int64) + 1
    nupd = as_value(in_num_updates).astype(jnp.int64) + 1
    old = as_value(in_old_num_accumulates).astype(jnp.int64)
    roll = num >= min(int(average_window * 1.5), max_average_window)
    s2 = jnp.where(roll, _f32(in_sum_2) + s1, _f32(in_sum_2))
    s1 = jnp.where(roll, jnp.zeros_like(s1), s1)
    old = jnp.where(roll, old + num, old)
    num = jnp.where(roll, jnp.zeros_like(num), num)
    _assign(in_sum_1, s1)
    _assign(in_sum_2, s2)
    _assign(in_sum_3, _f32(in_sum_3))
    _assign(in_num_accumulates, num)
    _assign(in_old_num_accumulates, old)
    _assign(in_num_updates, nupd)
    return in_sum_1


def check_finite_and_unscale_(xs, scale, name=None):
    """Parity: reference check_finite_and_unscale_ op — divide grads by
    the loss scale; found_infinite reports any non-finite value."""
    inv = 1.0 / _f32(scale)
    found = jnp.asarray(False)
    for x in xs:
        v = _f32(x) * inv
        found = found | jnp.any(~jnp.isfinite(v))
        _assign(x, v.astype(as_value(x).dtype))
    return xs, wrap(found)


def update_loss_scaling_(xs, found_infinite, prev_loss_scaling,
                         in_good_steps, in_bad_steps,
                         incr_every_n_steps=1000,
                         decr_every_n_nan_or_inf=2, incr_ratio=2.0,
                         decr_ratio=0.5, stop_update=False, name=None):
    """Parity: reference update_loss_scaling_ op (dynamic loss-scale
    state machine)."""
    inf = as_value(found_infinite)
    scale = _f32(prev_loss_scaling)
    good = as_value(in_good_steps).astype(jnp.int32)
    bad = as_value(in_bad_steps).astype(jnp.int32)
    bad = jnp.where(inf, bad + 1, 0)
    good = jnp.where(inf, 0, good + 1)
    decr = bad >= decr_every_n_nan_or_inf
    incr = good >= incr_every_n_steps
    scale = jnp.where(decr, jnp.maximum(scale * decr_ratio, 1.0), scale)
    scale = jnp.where(incr, scale * incr_ratio, scale)
    bad = jnp.where(decr, 0, bad)
    good = jnp.where(incr, 0, good)
    if not stop_update:
        for x in xs:
            _assign(x, jnp.where(inf, jnp.zeros_like(_f32(x)),
                                 _f32(x)).astype(as_value(x).dtype))
    _assign(prev_loss_scaling, scale)
    _assign(in_good_steps, good)
    _assign(in_bad_steps, bad)
    return xs, prev_loss_scaling


_OPTIM_OPS = [
    ("sgd_", sgd_), ("momentum_", momentum_), ("adam_", adam_),
    ("adamw_", adamw_), ("adagrad_", adagrad_), ("adadelta_", adadelta_),
    ("adamax_", adamax_), ("rmsprop_", rmsprop_), ("rprop_", rprop_),
    ("lamb_", lamb_), ("merged_adam_", merged_adam_),
    ("merged_momentum_", merged_momentum_), ("fused_adam_", fused_adam_),
    ("average_accumulates_", average_accumulates_),
    ("check_finite_and_unscale_", check_finite_and_unscale_),
    ("update_loss_scaling_", update_loss_scaling_),
]


def register_optim_ops():
    from .registry import register, registered_ops
    for name, fn in _OPTIM_OPS:
        if name not in registered_ops():
            register(name, fn, category="optimizer")
