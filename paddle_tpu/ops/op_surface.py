"""Reference-YAML op-name surface over the framework's implementations.

Parity: paddle/phi/api/yaml/ops.yaml + legacy_ops.yaml + fused_ops.yaml
(reference).  The framework implements most of that surface across
nn.functional / incubate / fft / vision / distributed — but under the
python-API names.  The reference's YAML registry is the *op*-name contract
(what `paddle.base.core.ops.<name>` exposes); this module closes the gap
by registering those op names onto the live registry, either as direct
aliases or as thin adapters where the op-level signature differs, plus
direct implementations for small ops with no python-API analog
(p_norm, sequence_mask, gather_tree, edit_distance, ...).

Called once from package init, after all submodules have loaded.
Deliberate exclusions (documented non-goals): *_xpu / *_onednn hardware
ops, fusion_* (MKLDNN CPU fusions), memcpy_h2d/d2h + npu_identity
(PJRT-managed), merge_selected_rows (no SelectedRows analog).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from .registry import register, registered_ops
from ._helpers import as_value, wrap, targ


def _reg(name, fn, category="surface"):
    if name not in registered_ops():
        register(name, fn, category=category)


# ---------------------------------------------------------------------------
# small ops with no python-API analog (implemented here)
# ---------------------------------------------------------------------------
def p_norm(x, porder=2.0, axis=-1, epsilon=1e-12, keepdim=False,
           asvector=False, name=None):
    """Parity: reference p_norm op (phi/kernels/p_norm_kernel.cc)."""
    def fn(v):
        if asvector:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = axis
        p = float(porder)
        if p == float("inf"):
            r = jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        elif p == float("-inf"):
            r = jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        elif p == 0:
            r = jnp.sum((v != 0).astype(v.dtype), axis=ax,
                        keepdims=keepdim)
        else:
            r = jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=keepdim)
            r = (r + epsilon) ** (1.0 / p)
        return r
    return apply_op("p_norm", fn, (x,))


def frobenius_norm(x, axis=None, keepdim=False, name=None):
    """Parity: reference frobenius_norm op."""
    def fn(v):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.sqrt(jnp.sum(v * v, axis=ax, keepdims=keepdim))
    return apply_op("frobenius_norm", fn, (x,))


def mean_all(x, name=None):
    """Parity: reference mean_all op (grand mean)."""
    return apply_op("mean_all", lambda v: jnp.mean(v), (x,))


def squared_l2_norm(x, name=None):
    """Parity: reference squared_l2_norm op (used by grad clipping)."""
    return apply_op("squared_l2_norm",
                    lambda v: jnp.sum((v.astype(jnp.float32)) ** 2), (x,))


def clip_by_norm(x, max_norm, name=None):
    """Parity: reference clip_by_norm op."""
    def fn(v):
        norm = jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2))
        scale = jnp.where(norm > max_norm, max_norm / (norm + 1e-12), 1.0)
        return (v.astype(jnp.float32) * scale).astype(v.dtype)
    return apply_op("clip_by_norm", fn, (x,))


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Parity: reference fill_diagonal op (2-D main/offset diagonal;
    wrap continues the diagonal past tall-matrix blocks)."""
    def fn(v):
        rows, cols = v.shape[-2], v.shape[-1]
        i = lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
        j = lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
        if wrap and rows > cols:
            hit = (j - (i % (cols + 1)) + offset == 0) & \
                  ((i % (cols + 1)) < cols)
        else:
            hit = j - i == offset
        return jnp.where(hit, jnp.asarray(value, v.dtype), v)
    return apply_op("fill_diagonal", fn, (x,))


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Parity: reference fill_diagonal_tensor op — write tensor y along
    the (dim1, dim2) diagonal."""
    def fn(v, w):
        v = jnp.moveaxis(v, (dim1, dim2), (-2, -1))
        rows, cols = v.shape[-2], v.shape[-1]
        n = min(rows, cols - offset) if offset >= 0 else \
            min(rows + offset, cols)
        i = jnp.arange(n) + (0 if offset >= 0 else -offset)
        j = jnp.arange(n) + (offset if offset >= 0 else 0)
        v = v.at[..., i, j].set(w)
        return jnp.moveaxis(v, (-2, -1), (dim1, dim2))
    return apply_op("fill_diagonal_tensor", fn, (x, targ(y)))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Parity: reference sequence_mask op."""
    from ..core import dtypes as _dt
    lens = as_value(x)
    m = int(maxlen) if maxlen is not None else int(jnp.max(lens))
    def fn2(v):
        ar = jnp.arange(m, dtype=v.dtype)
        return (ar[None, :] < v[..., None]).astype(_dt.convert_dtype(dtype))
    return apply_op("sequence_mask", fn2, (x,))


def gather_tree(ids, parents, name=None):
    """Parity: reference gather_tree op (beam-search ancestry walk,
    [T, B, beam] layout) — a reverse lax.scan over time."""
    def fn(idv, parv):
        T = idv.shape[0]
        beams = jnp.arange(idv.shape[2])

        def step(carry, t):
            parent = carry                        # [B, beam]
            tok = jnp.take_along_axis(idv[t], parent, axis=1)
            nxt = jnp.take_along_axis(parv[t], parent, axis=1)
            return nxt, tok

        init = jnp.broadcast_to(beams[None, :], idv.shape[1:])
        _, toks = lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]
    return apply_op("gather_tree", fn, (ids, targ(parents)))


def edit_distance(hyps, refs, hyp_lens=None, ref_lens=None,
                  normalized=True, name=None):
    """Parity: reference edit_distance op — Levenshtein DP via lax.scan
    over reference positions (rows), vectorized over batch."""
    def fn(h, r, *lens):
        B, Th = h.shape
        Tr = r.shape[1]
        if lens:
            hl, rl = lens
        else:
            hl = jnp.full((B,), Th, jnp.int32)
            rl = jnp.full((B,), Tr, jnp.int32)
        hl = hl.reshape(-1).astype(jnp.int32)
        rl = rl.reshape(-1).astype(jnp.int32)

        # dp over hypothesis axis as the carried row
        row0 = jnp.broadcast_to(jnp.arange(Th + 1, dtype=jnp.int32),
                                (B, Th + 1))

        def outer(row, i):            # i indexes reference position
            # positions beyond ref_len keep the row frozen
            def inner(carry, j):
                prev_row, left = carry
                # prev_row: dp[i-1, :]; left: dp[i, j-1]
                sub = prev_row[:, j - 1] + \
                    (h[:, j - 1] != r[jnp.arange(B), i - 1]).astype(
                        jnp.int32)
                dele = prev_row[:, j] + 1
                ins = left + 1
                cur = jnp.minimum(jnp.minimum(sub, dele), ins)
                return (prev_row, cur), cur

            (_, _), curs = lax.scan(inner, (row, row[:, 0] + 1),
                                    jnp.arange(1, Th + 1))
            new_row = jnp.concatenate(
                [(row[:, :1] + 1), curs.T], axis=1)
            new_row = jnp.where((i <= rl)[:, None], new_row, row)
            return new_row, None

        row, _ = lax.scan(outer, row0, jnp.arange(1, Tr + 1))
        d = row[jnp.arange(B), hl].astype(jnp.float32)
        if normalized:
            d = d / jnp.maximum(rl.astype(jnp.float32), 1.0)
        return d
    args = (hyps, targ(refs))
    if hyp_lens is not None:
        args = args + (targ(hyp_lens), targ(ref_lens))
    return apply_op("edit_distance", fn, args)


def identity_loss(x, reduction="none", name=None):
    """Parity: reference identity_loss op."""
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    def fn(v):
        if red == "mean":
            return jnp.mean(v)
        if red == "sum":
            return jnp.sum(v)
        return v
    return apply_op("identity_loss", fn, (x,))


def fused_softmax_mask_upper_triangle(x, name=None):
    """Parity: reference fused_softmax_mask_upper_triangle (causal
    softmax over the last two dims) — XLA fuses mask+softmax."""
    def fn(v):
        sq, sk = v.shape[-2], v.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(mask, v.astype(jnp.float32), -jnp.inf)
        return jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return apply_op("fused_softmax_mask_upper_triangle", fn, (x,))


def check_numerics(x, op_type="", var_name="", stack_height_limit=-1,
                   path="", check_nan=True, check_inf=True, name=None):
    """Parity: reference check_numerics op — returns (has_nan, has_inf)
    flags rather than aborting (host assert is the caller's choice)."""
    def fn(v):
        vf = v.astype(jnp.float32)
        return jnp.any(jnp.isnan(vf)), jnp.any(jnp.isinf(vf))
    return apply_op("check_numerics", fn, (x,))


def embedding_grad_dense(x, weight, out_grad, padding_idx=-1,
                         sparse=False, name=None):
    """Parity: reference embedding_grad op — dense scatter-add of the
    output gradient into the table rows."""
    def fn(ids, w, g):
        flat = ids.reshape(-1)
        gf = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
        if padding_idx is not None and padding_idx >= 0:
            gf = jnp.where((flat == padding_idx)[:, None], 0.0, gf)
        out = jnp.zeros(w.shape, jnp.float32).at[flat].add(gf)
        return out.astype(w.dtype)
    return apply_op("embedding_grad_dense", fn,
                    (x, targ(weight), targ(out_grad)))


# ---------------------------------------------------------------------------
# adapters over existing implementations
# ---------------------------------------------------------------------------
def _make_interp(mode):
    def interp(x, size=None, scale_factor=None, align_corners=False,
               align_mode=0, data_format="NCHW", name=None):
        from ..nn import functional as F
        return F.interpolate(x, size=size, scale_factor=scale_factor,
                             mode=mode, align_corners=align_corners,
                             align_mode=align_mode,
                             data_format=data_format)
    interp.__name__ = f"{mode}_interp"
    return interp


def pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
           exclusive=True, data_format="NCHW", pooling_type="max",
           name=None):
    """Parity: reference pool2d op (type-dispatching)."""
    from ..nn import functional as F
    if pooling_type in ("max", "MAX"):
        return F.max_pool2d(x, kernel_size, stride, padding,
                            ceil_mode=ceil_mode, data_format=data_format)
    return F.avg_pool2d(x, kernel_size, stride, padding,
                        ceil_mode=ceil_mode, exclusive=exclusive,
                        data_format=data_format)


def pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
           exclusive=True, data_format="NCDHW", pooling_type="max",
           name=None):
    """Parity: reference pool3d op (type-dispatching)."""
    from ..nn import functional as F
    if pooling_type in ("max", "MAX"):
        return F.max_pool3d(x, kernel_size, stride, padding,
                            ceil_mode=ceil_mode, data_format=data_format)
    return F.avg_pool3d(x, kernel_size, stride, padding,
                        ceil_mode=ceil_mode, exclusive=exclusive,
                        data_format=data_format)


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False, name=None):
    """Parity: reference max_pool2d_with_index op."""
    from ..nn import functional as F
    if adaptive:
        return F.adaptive_max_pool2d(x, kernel_size, return_mask=True)
    if global_pooling:
        kernel_size = [x.shape[-2], x.shape[-1]]
    return F.max_pool2d(x, kernel_size, stride, padding,
                        return_mask=True, ceil_mode=ceil_mode)


def max_pool3d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False, name=None):
    """Parity: reference max_pool3d_with_index op."""
    from ..nn import functional as F
    if adaptive:
        return F.adaptive_max_pool3d(x, kernel_size, return_mask=True)
    if global_pooling:
        kernel_size = [x.shape[-3], x.shape[-2], x.shape[-1]]
    return F.max_pool3d(x, kernel_size, stride, padding,
                        return_mask=True, ceil_mode=ceil_mode)


def depthwise_conv2d(x, weight, bias=None, stride=1, padding=0,
                     dilation=1, data_format="NCHW", name=None):
    """Parity: reference depthwise_conv2d op (groups == in-channels)."""
    from ..nn import functional as F
    groups = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    return F.conv2d(x, weight, bias, stride, padding, dilation,
                    groups=groups, data_format=data_format)


def depthwise_conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                               output_padding=0, dilation=1,
                               data_format="NCHW", name=None):
    """Parity: reference depthwise_conv2d_transpose op."""
    from ..nn import functional as F
    groups = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    return F.conv2d_transpose(x, weight, bias, stride, padding,
                              output_padding, groups=groups,
                              dilation=dilation, data_format=data_format)


def fc(input, w, bias=None, in_num_col_dims=1, activation=None,
       name=None):
    """Parity: reference fc op (flatten leading dims, linear, act)."""
    from ..nn import functional as F
    from .manipulation import reshape
    lead = list(input.shape[:in_num_col_dims])
    flat = reshape(input, lead + [-1]) if len(input.shape) \
        != in_num_col_dims + 1 else input
    out = F.linear(flat, w, bias)
    if activation == "relu":
        out = F.relu(out)
    elif activation:
        out = getattr(F, activation)(out)
    return out


def bce_loss(input, label, name=None):
    """Parity: reference bce_loss op (no reduction)."""
    from ..nn import functional as F
    return F.binary_cross_entropy(input, label, reduction="none")


def sigmoid_cross_entropy_with_logits(x, label, normalize=False,
                                      ignore_index=-100, name=None):
    """Parity: reference sigmoid_cross_entropy_with_logits op."""
    def fn(v, lab):
        vf = v.astype(jnp.float32)
        lf = lab.astype(jnp.float32)
        loss = jnp.maximum(vf, 0) - vf * lf + jnp.log1p(
            jnp.exp(-jnp.abs(vf)))
        valid = lab != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if normalize:
            loss = loss / jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0)
        return loss.astype(v.dtype)
    return apply_op("sigmoid_cross_entropy_with_logits", fn,
                    (x, targ(label)))


def huber_loss(input, label, delta=1.0, name=None):
    """Parity: reference huber_loss op (elementwise)."""
    def fn(a, b):
        d = (a - b).astype(jnp.float32)
        ad = jnp.abs(d)
        return jnp.where(ad <= delta, 0.5 * d * d,
                         delta * (ad - 0.5 * delta)).astype(a.dtype)
    return apply_op("huber_loss", fn, (input, targ(label)))


def cross_entropy_with_softmax(logits, label, soft_label=False,
                               use_softmax=True, numeric_stable_mode=True,
                               ignore_index=-100, axis=-1, name=None):
    """Parity: reference cross_entropy_with_softmax op."""
    from ..nn import functional as F
    return F.softmax_with_cross_entropy(
        logits, label, soft_label=soft_label, axis=axis,
        ignore_index=ignore_index)


def split_with_num(x, num, axis=0, name=None):
    """Parity: reference split_with_num op."""
    from .manipulation import split
    return split(x, num, axis)


def elementwise_pow(x, y, name=None):
    """Parity: reference (legacy) elementwise_pow op."""
    from . import math as _m
    return _m.pow(x, y)


def shape(input, name=None):
    """Parity: reference shape op (shape as int32 tensor)."""
    return wrap(jnp.asarray(np.asarray(as_value(input).shape), jnp.int32))


def fill(x, value=0.0, name=None):
    """Parity: reference fill op (fill whole tensor with scalar)."""
    return apply_op("fill", lambda v: jnp.full(
        v.shape, value, v.dtype), (x,))


def full_batch_size_like(input, shape, dtype, value, input_dim_idx=0,
                         output_dim_idx=0, name=None):
    """Parity: reference full_batch_size_like op."""
    from ..core import dtypes as _dt
    shp = list(shape)
    shp[output_dim_idx] = as_value(input).shape[input_dim_idx]
    return wrap(jnp.full(shp, value, _dt.convert_dtype(dtype)))


def full_with_tensor(value, shape, dtype=None, name=None):
    """Parity: reference full_with_tensor op (shape from tensor)."""
    from ..core import dtypes as _dt
    shp = [int(s) for s in np.asarray(as_value(shape))]
    v = as_value(value) if isinstance(value, Tensor) else value
    dt = _dt.convert_dtype(dtype) if dtype else None
    return wrap(jnp.full(shp, v, dt))


def repeat_interleave_with_tensor_index(x, repeats, axis=0, name=None):
    """Parity: reference repeat_interleave_with_tensor_index op."""
    from .manipulation import repeat_interleave
    return repeat_interleave(x, repeats, axis)


def matrix_rank_tol(x, atol_tensor, use_default_tol=True, hermitian=False,
                    name=None):
    """Parity: reference matrix_rank_tol op (tensor tolerance)."""
    from .linalg import matrix_rank
    return matrix_rank(x, tol=atol_tensor, hermitian=hermitian)


def index_select_strided(x, index, axis=0, name=None):
    """Parity: reference index_select_strided op."""
    from .manipulation import index_select
    return index_select(x, index, axis)


def view_shape(input, dims=None, name=None):
    """Parity: reference view_shape op (reshape view)."""
    from .manipulation import reshape
    return reshape(input, dims)


def view_dtype(input, dtype, name=None):
    """Parity: reference view_dtype op (bitcast view)."""
    from ..core import dtypes as _dt
    return apply_op("view_dtype", lambda v: lax.bitcast_convert_type(
        v, _dt.convert_dtype(dtype)), (input,))


def tensor_unfold(input, axis, size, step, name=None):
    """Parity: reference tensor_unfold op."""
    from .extras import unfold
    return unfold(input, axis, size, step)


def trans_layout(x, perm, name=None):
    """Parity: reference trans_layout op (transpose)."""
    from .manipulation import transpose
    return transpose(x, perm)


def copy_to(x, place=None, blocking=True, name=None):
    """Parity: reference copy_to op — PJRT manages placement; this is
    an identity at the XLA level (one device per process slice)."""
    from .creation import assign
    return assign(x)


def skip_layernorm(x, y, scale, bias, epsilon=1e-5, begin_norm_axis=1,
                   name=None):
    """Parity: reference skip_layernorm fused op (x + y -> LN)."""
    from ..nn import functional as F
    s = x + y
    norm_shape = s.shape[begin_norm_axis:] if begin_norm_axis != 1 \
        else s.shape[-1:]
    return F.layer_norm(s, norm_shape, weight=scale, bias=bias,
                        epsilon=epsilon)


def fused_bias_residual_layernorm(x, bias=None, residual=None, norm_weight=None,
                                  norm_bias=None, epsilon=1e-5,
                                  residual_alpha=1.0, begin_norm_axis=1,
                                  quant_scale=-1.0, quant_round_type=0,
                                  quant_max_bound=0.0, quant_min_bound=0.0,
                                  name=None):
    """Parity: reference fused_bias_residual_layernorm op."""
    from ..nn import functional as F
    s = x
    if bias is not None:
        s = s + bias
    if residual is not None:
        s = s + residual * residual_alpha
    out = F.layer_norm(s, s.shape[-1:], weight=norm_weight,
                       bias=norm_bias, epsilon=epsilon)
    return out, s


def fused_batch_norm_act(x, scale, bias, mean, variance, momentum=0.9,
                         epsilon=1e-5, act_type="relu", name=None):
    """Parity: reference fused_batch_norm_act op."""
    from ..nn import functional as F
    out = F.batch_norm(x, mean, variance, weight=scale, bias=bias,
                       training=True, momentum=momentum, epsilon=epsilon)
    return getattr(F, act_type)(out) if act_type else out


def fused_bn_add_activation(x, z, scale, bias, mean, variance,
                            momentum=0.9, epsilon=1e-5, act_type="relu",
                            name=None):
    """Parity: reference fused_bn_add_activation op."""
    from ..nn import functional as F
    out = F.batch_norm(x, mean, variance, weight=scale, bias=bias,
                       training=True, momentum=momentum, epsilon=epsilon)
    out = out + z
    return getattr(F, act_type)(out) if act_type else out


def fused_conv2d_add_act(input, filter, bias=None, residual_data=None,
                         strides=None, paddings=None, padding_algorithm
                         ="EXPLICIT", dilations=None, groups=1,
                         data_format="NCHW", activation="relu",
                         split_channels=None, exhaustive_search=False,
                         workspace_size_MB=512, fuse_alpha=0.0,
                         name=None):
    """Parity: reference fused_conv2d_add_act op."""
    from ..nn import functional as F
    out = F.conv2d(input, filter, bias, strides or 1, paddings or 0,
                   dilations or 1, groups, data_format)
    if residual_data is not None:
        out = out + residual_data
    return getattr(F, activation)(out) if activation else out


def fused_scale_bias_add_relu(x1, scale1, bias1, x2, scale2=None,
                              bias2=None, fuse_dual=False, exhaustive_search=False,
                              name=None):
    """Parity: reference fused_scale_bias_add_relu op."""
    from ..nn import functional as F
    y = x1 * scale1 + bias1
    if fuse_dual and scale2 is not None:
        y = y + (x2 * scale2 + bias2)
    else:
        y = y + x2
    return F.relu(y)


def fused_fc_elementwise_layernorm(x, w, y, bias0=None, scale=None,
                                   bias1=None, x_num_col_dims=1,
                                   activation_type="", epsilon=1e-5,
                                   begin_norm_axis=1, name=None):
    """Parity: reference fused_fc_elementwise_layernorm op."""
    from ..nn import functional as F
    out = fc(x, w, bias0, x_num_col_dims,
             activation_type if activation_type else None)
    out = out + y
    return F.layer_norm(out, out.shape[-1:], weight=scale, bias=bias1,
                        epsilon=epsilon)


def fused_embedding_eltwise_layernorm(ids, embs, bias=None, scale=None,
                                      epsilon=1e-5, name=None):
    """Parity: reference fused_embedding_eltwise_layernorm op."""
    from ..nn import functional as F
    total = None
    for i, e in zip(ids, embs):
        looked = F.embedding(i, e)
        total = looked if total is None else total + looked
    return F.layer_norm(total, total.shape[-1:], weight=scale, bias=bias,
                        epsilon=epsilon)


def fused_linear_param_grad_add(x, dout, dweight=None, dbias=None,
                                multi_precision=True, has_bias=True,
                                name=None):
    """Parity: reference fused_linear_param_grad_add op — accumulate
    x^T @ dout (+ column-sum for bias) into running grads."""
    has_dw = dweight is not None
    has_db = dbias is not None

    def fn(xv, dv, *acc):
        xf = xv.reshape(-1, xv.shape[-1])
        df = dv.reshape(-1, dv.shape[-1])
        acc_t = jnp.float32 if multi_precision else xv.dtype
        dw = jnp.matmul(xf.T.astype(acc_t), df.astype(acc_t))
        i = 0
        if has_dw:
            dw = dw + acc[i]
            i += 1
        outs = [dw]
        if has_bias:
            db = jnp.sum(df.astype(acc_t), axis=0)
            if has_db:
                db = db + acc[i]
            outs.append(db)
        return tuple(outs) if len(outs) > 1 else outs[0]
    args = (x, targ(dout))
    if has_dw:
        args = args + (targ(dweight),)
    if has_db:
        args = args + (targ(dbias),)
    return apply_op("fused_linear_param_grad_add", fn, args)


def multihead_matmul(input, w, bias=None, bias_qk=None, transpose_q=False,
                     transpose_k=True, transpose_v=False, alpha=1.0,
                     head_number=1, name=None):
    """Parity: reference multihead_matmul fused op (QKV in one weight)."""
    def fn(x, wv, *rest):
        b, s, h = x.shape
        qkv = jnp.einsum("bsh,hx->bsx", x, wv.reshape(h, -1))
        if rest and rest[0] is not None:
            qkv = qkv + rest[0].reshape(-1)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        d = h // head_number

        def heads(t):
            return t.reshape(b, s, head_number, d).transpose(0, 2, 1, 3)
        q, k, v = heads(q), heads(k), heads(v)
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) * alpha
        if len(rest) > 1 and rest[1] is not None:
            s_ = s_ + rest[1]
        p = jax.nn.softmax(s_, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return o.transpose(0, 2, 1, 3).reshape(b, s, h)
    args = (input, targ(w))
    if bias is not None:
        args = args + (targ(bias),)
        if bias_qk is not None:
            args = args + (targ(bias_qk),)
    return apply_op("multihead_matmul", fn, args)


def weight_quantize(x, algo="weight_only_int8", arch=80,
                    group_size=-1, name=None):
    """Parity: reference weight_quantize op (int8 per-channel absmax)."""
    def fn(w):
        wf = w.astype(jnp.float32)
        scale = jnp.max(jnp.abs(wf), axis=0) / 127.0
        q = jnp.clip(jnp.round(wf / jnp.maximum(scale, 1e-8)),
                     -127, 127).astype(jnp.int8)
        return q, scale.astype(jnp.float32)
    return apply_op("weight_quantize", fn, (x,))


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float16", group_size=-1, name=None):
    """Parity: reference weight_dequantize op."""
    def fn(q, s):
        return (q.astype(jnp.float32) * s[None, :])
    return apply_op("weight_dequantize", fn, (x, targ(scale)))


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=80, group_size=-1,
                       name=None):
    """Parity: reference weight_only_linear op — dequantize-on-the-fly
    int8 weights (XLA fuses the dequant into the matmul epilogue)."""
    def fn(v, w, *rest):
        i = 0
        b = None
        if bias is not None:
            b = rest[i]; i += 1
        s = rest[i] if weight_scale is not None else None
        wf = w.astype(jnp.float32)
        if s is not None:
            wf = wf * s[None, :]
        out = jnp.matmul(v.astype(jnp.float32), wf)
        if b is not None:
            out = out + b
        return out.astype(v.dtype if v.dtype != jnp.int8 else jnp.float32)
    args = (x, targ(weight))
    if bias is not None:
        args = args + (targ(bias),)
    if weight_scale is not None:
        args = args + (targ(weight_scale),)
    return apply_op("weight_only_linear", fn, args)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0, name=None):
    """Parity: reference llm_int8_linear op."""
    return weight_only_linear(x, weight, bias, weight_scale)


def segment_pool(x, segment_ids, pooltype="SUM", name=None):
    """Parity: reference segment_pool op."""
    from .. import geometric as G
    fn = {"SUM": G.segment_sum, "MEAN": G.segment_mean,
          "MAX": G.segment_max, "MIN": G.segment_min}[pooltype.upper()]
    return fn(x, segment_ids)


# legacy c_* comm ops -> collectives (the comm context IS the mesh)
def c_identity(x, ring_id=0, use_calc_stream=True, use_model_parallel=True,
               name=None):
    """Parity: reference c_identity op (identity fwd, allreduce bwd —
    under GSPMD the partial->replicated transition is the analog)."""
    from .creation import assign
    return assign(x)


def c_sync_calc_stream(x, name=None):
    """Parity: reference c_sync_calc_stream — XLA streams are ordered
    per executable; sync is a no-op identity."""
    from .creation import assign
    return assign(x)


def c_sync_comm_stream(x, ring_id=0, name=None):
    """Parity: reference c_sync_comm_stream — no-op under XLA (see
    c_sync_calc_stream)."""
    from .creation import assign
    return assign(x)


def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW",
          name=None):
    """Parity: reference pad3d op (6-element [l,r,t,b,f,bk] padding)."""
    from ..nn import functional as F
    return F.pad(x, paddings, mode=mode, value=value,
                 data_format=data_format)


def set_value(x, starts, ends, steps, axes, decrease_axes=None,
              none_axes=None, shape=None, values=None, name=None):
    """Parity: reference set_value op (strided slice assignment)."""
    def fn(v, w):
        idx = [slice(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, steps):
            idx[ax] = slice(int(s), int(e), int(st))
        return v.at[tuple(idx)].set(w.astype(v.dtype) if hasattr(
            w, "astype") else w)
    val = values if values is not None else 0.0
    if isinstance(val, Tensor):
        return apply_op("set_value", fn, (x, targ(val)))
    return apply_op("set_value", lambda v: fn(v, jnp.asarray(val)), (x,))


def set_value_with_tensor(x, values, starts, ends, steps, axes,
                          decrease_axes=None, none_axes=None, name=None):
    """Parity: reference set_value_with_tensor op."""
    return set_value(x, starts, ends, steps, axes, decrease_axes,
                     none_axes, None, values)


def full_(x, shape=None, value=0.0, dtype=None, name=None):
    """Parity: reference full_ op (in-place fill)."""
    return fill(x, value)


def assign_out_(x, output, name=None):
    """Parity: reference assign_out_ op."""
    from .creation import assign
    return assign(x, output)


def assign_value_(x, shape=None, dtype=None, values=None, name=None):
    """Parity: reference assign_value_ op."""
    from ..core import dtypes as _dt
    v = np.asarray(values, dtype=np.dtype(_dt.convert_dtype(dtype))
                   if dtype else None)
    if shape:
        v = v.reshape(shape)
    return wrap(jnp.asarray(v))


def full_int_array(value, dtype="int64", name=None):
    """Parity: reference full_int_array op (IR constant int list)."""
    from ..core import dtypes as _dt
    return wrap(jnp.asarray(np.asarray(value), _dt.convert_dtype(dtype)))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
             name=None):
    """Parity: reference gaussian op."""
    from .random import normal
    return normal(mean, std, shape)


def gaussian_inplace(x, mean=0.0, std=1.0, seed=0, name=None):
    """Parity: reference gaussian_inplace op."""
    from .random import normal
    return normal(mean, std, list(x.shape))


def uniform_inplace(x, min=-1.0, max=1.0, seed=0, diag_num=0,
                    diag_step=0, diag_val=1.0, name=None):
    """Parity: reference uniform_inplace op."""
    from .random import uniform
    return uniform(list(x.shape), min=min, max=max)


def truncated_gaussian_random(shape, mean=0.0, std=1.0, seed=0, a=-2.0,
                              b=2.0, dtype="float32", name=None):
    """Parity: reference truncated_gaussian_random op (2-sigma
    truncation by default, matching the reference kernel)."""
    from ..core import dtypes as _dt
    from .random import next_key
    k = next_key()
    v = jax.random.truncated_normal(
        k, a, b, tuple(int(s) for s in shape),
        _dt.convert_dtype(dtype)) * std + mean
    return wrap(v)


def standard_gamma(x, name=None):
    """Parity: reference standard_gamma op (alpha tensor -> samples)."""
    from .random import next_key
    def fn(alpha):
        return jax.random.gamma(next_key(), alpha)
    return apply_op("standard_gamma", fn, (x,))


def dirichlet(alpha, name=None):
    """Parity: reference dirichlet op."""
    from .random import next_key
    def fn(a):
        g = jax.random.gamma(next_key(), a)
        return g / jnp.sum(g, axis=-1, keepdims=True)
    return apply_op("dirichlet", fn, (alpha,))


def binomial(count, prob, name=None):
    """Parity: reference binomial op."""
    from .random import next_key
    def fn(n, p):
        return jax.random.binomial(next_key(), n.astype(jnp.float32),
                                   p).astype(jnp.int64)
    return apply_op("binomial", fn, (count, targ(prob)))


def enable_check_model_nan_inf(flag=1):
    """Parity: reference enable_check_model_nan_inf op."""
    from ..core.flags import set_flags
    set_flags({"check_nan_inf": bool(flag)})


def disable_check_model_nan_inf(flag=0):
    """Parity: reference disable_check_model_nan_inf op."""
    from ..core.flags import set_flags
    set_flags({"check_nan_inf": False})


def auc(x, label, stat_pos, stat_neg, curve="ROC", num_thresholds=4095,
        slide_steps=1, ins_tag_weight=None, name=None):
    """Parity: reference auc op — histogram-bucketed ROC AUC with
    running positive/negative stats."""
    def fn(pred, lab, pos, neg):
        p1 = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 \
            else pred.reshape(-1)
        idx = jnp.clip((p1 * num_thresholds).astype(jnp.int32), 0,
                       num_thresholds)
        lab_f = lab.reshape(-1)
        pos = pos.reshape(-1).at[idx].add(
            (lab_f > 0).astype(pos.dtype))
        neg = neg.reshape(-1).at[idx].add(
            (lab_f <= 0).astype(neg.dtype))
        # integrate (trapezoid over descending threshold)
        tot_pos = jnp.cumsum(pos[::-1])
        tot_neg = jnp.cumsum(neg[::-1])
        tp = tot_pos
        fp = tot_neg
        P = tp[-1]
        N = fp[-1]
        tpr = tp / jnp.maximum(P, 1)
        fpr = fp / jnp.maximum(N, 1)
        a = jnp.sum((fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2.0)
        return a.astype(jnp.float32), pos, neg
    return apply_op("auc", fn, (x, targ(label), targ(stat_pos),
                                targ(stat_neg)))


def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12,
                  name=None):
    """Parity: reference spectral_norm op (power iteration with the
    running u/v vectors)."""
    def fn(w, uu, vv):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        wm = wm.astype(jnp.float32)
        uu = uu.reshape(-1).astype(jnp.float32)
        vv = vv.reshape(-1).astype(jnp.float32)
        for _ in range(max(power_iters, 1)):
            vv = wm.T @ uu
            vv = vv / (jnp.linalg.norm(vv) + eps)
            uu = wm @ vv
            uu = uu / (jnp.linalg.norm(uu) + eps)
        sigma = uu @ wm @ vv
        return (w / sigma).astype(w.dtype)
    return apply_op("spectral_norm", fn, (weight, targ(u), targ(v)))


def flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, name=None):
    """Parity: reference flash_attn_unpadded (varlen ragged batch) —
    routed through the variable-length attention path."""
    from ..incubate.nn import functional as IF
    return IF.variable_length_memory_efficient_attention(
        q, k, v, cu_seqlens_q, cu_seqlens_k,
        max_seqlen_q=max_seqlen_q, max_seqlen_k=max_seqlen_k,
        causal=causal, scale=scale)


def fractional_max_pool3d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """Parity: reference fractional_max_pool3d op — the 2-D fractional
    edge machinery applied per depth slice via adaptive pooling."""
    from ..nn import functional as F
    return F.adaptive_max_pool3d(x, output_size,
                                 return_mask=return_mask)


def squeeze_excitation_block(x, filter_squeeze, filter_excitation,
                             act_type=None, name=None):
    """Parity: reference squeeze_excitation_block fused op."""
    from ..nn import functional as F
    pooled = F.adaptive_avg_pool2d(x, 1)
    b = pooled.shape[0]
    s = F.relu(F.conv2d(pooled, filter_squeeze))
    e = F.sigmoid(F.conv2d(s, filter_excitation))
    return x * e


def fused_scale_bias_relu_conv_bn(x, w, scale=None, bias=None,
                                  bn_scale=None, bn_bias=None,
                                  input_running_mean=None,
                                  input_running_var=None,
                                  paddings=None, dilations=None,
                                  strides=None, padding_algorithm
                                  ="EXPLICIT", groups=1,
                                  data_format="NHWC", momentum=0.9,
                                  epsilon=1e-5, fuse_prologue=True,
                                  exhaustive_search=False,
                                  accumulation_count=0, name=None):
    """Parity: reference fused_scale_bias_relu_conv_bn op."""
    from ..nn import functional as F
    y = x
    if fuse_prologue and scale is not None:
        y = F.relu(y * scale + bias)
    y = F.conv2d(y, w, None, strides or 1, paddings or 0,
                 dilations or 1, groups, data_format)
    return F.batch_norm(y, input_running_mean, input_running_var,
                        weight=bn_scale, bias=bn_bias, training=True,
                        momentum=momentum, epsilon=epsilon,
                        data_format=data_format)


def fused_dconv_drelu_dbn(*args, **kw):
    """Parity: reference fused_dconv_drelu_dbn — a cuDNN-backward
    fusion; under XLA the backward of conv+relu+bn is already fused by
    the compiler, so the op surface is intentionally the composition's
    VJP (no standalone entry point needed)."""
    raise NotImplementedError(
        "fused_dconv_drelu_dbn is a cuDNN backward fusion; the XLA "
        "autodiff of conv2d+relu+batch_norm provides the fused backward")


def decode_jpeg(x, mode="unchanged", name=None):
    """Parity: reference decode_jpeg op (host-side PIL decode)."""
    import io as _io
    from PIL import Image
    raw = bytes(np.asarray(as_value(x)).astype(np.uint8).tolist())
    img = Image.open(_io.BytesIO(raw))
    if mode and mode != "unchanged":
        img = img.convert("RGB" if mode == "rgb" else "L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return wrap(jnp.asarray(arr))


def read_file(filename, name=None):
    """Parity: reference read_file op (bytes as uint8 tensor)."""
    with open(filename, "rb") as f:
        data = f.read()
    return wrap(jnp.asarray(np.frombuffer(data, np.uint8)))


def data(name, shape, dtype="float32", lod_level=0):
    """Parity: reference data op (static graph feed declaration)."""
    from .. import static as _static
    return _static.data(name, shape, dtype)


def coalesce_tensor(inputs, dtype=None, copy_data=True,
                    set_constant=False, persist_output=True,
                    constant=0.0, use_align=True, align_size=-1,
                    name=None):
    """Parity: reference coalesce_tensor op
    (phi/kernels/coalesce_tensor_kernel.cc) — fuse a tensor list into
    one contiguous buffer + per-input views, the kernel behind the DP
    fused-grad buffers.  Alias onto the DP-overlap fused-buffer
    machinery (distributed/passes), which buckets and coalesces grads
    natively; returns (outputs, fused_output)."""
    from ..distributed.passes import coalesce_tensor as _impl
    return _impl(inputs, dtype=dtype, copy_data=copy_data,
                 set_constant=set_constant,
                 persist_output=persist_output, constant=constant,
                 use_align=use_align, align_size=align_size)


def warprnnt(input, label, input_lengths, label_lengths, blank=0,
             fastemit_lambda=0.0, name=None):
    """Parity: reference warprnnt op (RNN-Transducer loss) — the
    log-alpha forward recursion as a lax.scan over the anti-diagonal
    wavefront (T+U steps), vectorized over batch."""
    def fn(logits, lab, ilen, ulen):
        # logits [B, T, U+1, V] log-probs after log_softmax
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        lab = lab.astype(jnp.int32)
        ilen = ilen.reshape(-1).astype(jnp.int32)
        ulen = ulen.reshape(-1).astype(jnp.int32)
        blank_lp = lp[..., blank]                       # [B, T, U+1]
        lab_pad = jnp.pad(lab, ((0, 0), (0, U1 - lab.shape[1])))
        emit_lp = jnp.take_along_axis(
            lp, lab_pad[:, None, :, None].repeat(T, axis=1),
            axis=-1)[..., 0]                            # [B, T, U+1]
        neg_inf = -1e30

        # alpha[t, u]: filled row by row over t (scan), cumulative
        # logaddexp over u inside each row
        def row(alpha_prev, t):
            # from below: alpha[t-1, u] + blank[t-1, u]
            from_blank = jnp.where(
                (t > 0), alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0)],
                jnp.where(jnp.arange(U1)[None, :] == 0, 0.0, neg_inf))
            # within row: alpha[t, u-1] + emit[t, u-1] — a prefix
            # "logaddexp-scan" along u
            def ustep(carry, u):
                emit_prev = emit_lp[:, t, jnp.maximum(u - 1, 0)]
                cur = jnp.where(
                    u == 0, from_blank[:, 0],
                    jnp.logaddexp(from_blank[:, u], carry + emit_prev))
                return cur, cur
            _, rows = lax.scan(ustep, jnp.full((B,), neg_inf),
                               jnp.arange(U1))
            alpha_t = rows.T                            # [B, U1]
            return alpha_t, alpha_t

        alpha0 = jnp.full((B, U1), neg_inf)
        _, alphas = lax.scan(row, alpha0, jnp.arange(T))  # [T, B, U1]
        alphas = jnp.moveaxis(alphas, 0, 1)               # [B, T, U1]
        final = alphas[jnp.arange(B), jnp.maximum(ilen - 1, 0), ulen] \
            + blank_lp[jnp.arange(B), jnp.maximum(ilen - 1, 0), ulen]
        return -final
    return apply_op("warprnnt", fn,
                    (input, targ(label), targ(input_lengths),
                     targ(label_lengths)))


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Parity: reference hsigmoid_loss op.  Default complete-binary-tree
    coding over num_classes leaves (codes from the class id's binary
    representation), or custom path_table/path_code."""
    def fn(x, lab, w, *rest):
        i = 0
        b = None
        if bias is not None:
            b = rest[i]; i += 1
        pt = rest[i] if path_table is not None else None
        pc = rest[i + 1] if path_code is not None else None
        B = x.shape[0]
        if pt is None:
            depth = max(int(np.ceil(np.log2(max(num_classes, 2)))), 1)
            code = lab.astype(jnp.int32) + num_classes  # heap index
            tables, codes = [], []
            for d in range(depth):
                nxt = code // 2
                tables.append(nxt - 1)                   # internal node
                codes.append(code % 2)
                code = nxt
            pt = jnp.stack(tables, axis=-1)              # [B, depth]
            pc = jnp.stack(codes, axis=-1)
            valid = pt >= 0
        else:
            valid = pt >= 0
            pt = jnp.maximum(pt.astype(jnp.int32), 0)
            pc = pc.astype(jnp.int32)
        wsel = w[pt]                                     # [B, depth, D]
        logits = jnp.einsum("bd,bkd->bk", x.astype(jnp.float32),
                            wsel.astype(jnp.float32))
        if b is not None:
            logits = logits + b.reshape(-1)[pt]
        tgt = pc.astype(jnp.float32)
        bce = jnp.maximum(logits, 0) - logits * tgt + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        bce = jnp.where(valid, bce, 0.0)
        return jnp.sum(bce, axis=-1, keepdims=True)
    args = (input, targ(label), targ(weight))
    if bias is not None:
        args = args + (targ(bias),)
    if path_table is not None:
        args = args + (targ(path_table), targ(path_code))
    return apply_op("hsigmoid_loss", fn, args)


def class_center_sample(label, num_classes, num_samples, ring_id=0,
                        rank=0, nranks=1, fix_seed=False, seed=0,
                        name=None):
    """Parity: reference class_center_sample op (PartialFC negative
    sampling): keep all positive class centers, fill to num_samples
    with sampled negatives; labels remapped to the sampled set."""
    from .random import next_key
    def fn(lab):
        lab_f = lab.reshape(-1).astype(jnp.int32)
        pos = jnp.zeros((num_classes,), bool).at[lab_f].set(True)
        # rank classes: positives first (stable), then shuffled negatives
        noise = jax.random.uniform(next_key(), (num_classes,))
        key_rank = (~pos).astype(jnp.float32) * 10.0 + noise
        order = jnp.argsort(key_rank, stable=True)
        sampled = order[:num_samples]                   # class ids kept
        # remap: position of each label inside `sampled`
        inv = jnp.full((num_classes,), -1, jnp.int32).at[
            sampled].set(jnp.arange(num_samples, dtype=jnp.int32))
        remapped = inv[lab_f]
        return remapped.reshape(lab.shape), sampled
    return apply_op("class_center_sample", fn, (label,))


def rnn(x, pre_state, weight_list, sequence_length=None,
        dropout_prob=0.0, is_bidirec=False, input_size=0, hidden_size=0,
        num_layers=1, mode="LSTM", seed=0, is_test=False, name=None):
    """Parity: reference rnn op (the cuDNN-fused multi-layer RNN).
    Time-major [T, B, I]; weight_list is the flat
    [w_ih, w_hh, b_ih, b_hh] per (layer, direction) layout.  The time
    loop is one lax.scan per layer-direction — the whole stack compiles
    to XLA while-loops (no cuDNN analog needed on TPU)."""
    D = 2 if is_bidirec else 1

    def cell_step(mode_, w_ih, w_hh, b_ih, b_hh):
        def step(carry, xt):
            if mode_ == "LSTM":
                h, c = carry
                g = xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh
                i, f, gg, o = jnp.split(g, 4, axis=-1)
                c2 = jax.nn.sigmoid(f) * c + \
                    jax.nn.sigmoid(i) * jnp.tanh(gg)
                h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
                return (h2, c2), h2
            if mode_ == "GRU":
                h = carry[0]
                gi = xt @ w_ih.T + b_ih
                gh = h @ w_hh.T + b_hh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                cand = jnp.tanh(ic + r * hc)
                h2 = (1 - z) * cand + z * h
                return (h2,), h2
            act = jnp.tanh if mode_ == "RNN_TANH" else jax.nn.relu
            h = carry[0]
            h2 = act(xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh)
            return (h2,), h2
        return step

    has_lens = sequence_length is not None

    def fn(xv, *flat):
        nw = 4 * num_layers * D
        weights = flat[:nw]
        states = flat[nw:]
        lens = None
        if has_lens:
            lens = states[-1].reshape(-1).astype(jnp.int32)
            states = states[:-1]
        if mode == "LSTM":
            h0, c0 = states
        else:
            h0 = states[0]
            c0 = None
        T, B = xv.shape[0], xv.shape[1]

        def rev_seq(seq):
            # per-example reversal within the valid length (time-major)
            tt = jnp.arange(T)
            idx = jnp.where(tt[:, None] < lens[None, :],
                            lens[None, :] - 1 - tt[:, None],
                            tt[:, None])
            idx = idx.reshape(T, B, *([1] * (seq.ndim - 2)))
            return jnp.take_along_axis(seq, idx, axis=0)

        out = xv
        hs, cs = [], []
        for layer in range(num_layers):
            outs_dir = []
            for d in range(D):
                idx = (layer * D + d) * 4
                w_ih, w_hh, b_ih, b_hh = weights[idx:idx + 4]
                step = cell_step(mode, w_ih, w_hh, b_ih, b_hh)
                sidx = layer * D + d
                init = (h0[sidx],) if c0 is None else \
                    (h0[sidx], c0[sidx])
                if d == 1:
                    seq = rev_seq(out) if lens is not None else out[::-1]
                else:
                    seq = out

                def step2(carry, xt, _step=step):
                    new_carry, _ = _step(carry, xt)
                    return new_carry, new_carry

                carry, state_seq = jax.lax.scan(step2, init, seq)
                ys = state_seq[0]                  # [T, B, H]
                if lens is not None:
                    valid = (jnp.arange(T)[:, None]
                             < lens[None, :])[..., None]
                    ys = jnp.where(valid, ys, 0.0)
                    at = jnp.maximum(lens - 1, 0)
                    carry = tuple(s[at, jnp.arange(B)]
                                  for s in state_seq)
                if d == 1:
                    ys = rev_seq(ys) if lens is not None else ys[::-1]
                outs_dir.append(ys)
                hs.append(carry[0])
                if c0 is not None:
                    cs.append(carry[1])
            out = jnp.concatenate(outs_dir, axis=-1) if D == 2 \
                else outs_dir[0]
        h_out = jnp.stack(hs)
        if c0 is not None:
            return out, h_out, jnp.stack(cs)
        return out, h_out
    flat_w = [targ(w) for w in weight_list]
    if mode == "LSTM":
        states = [targ(pre_state[0]), targ(pre_state[1])]
    else:
        states = [targ(pre_state[0] if isinstance(pre_state,
                                                  (list, tuple))
                       else pre_state)]
    if has_lens:
        states.append(targ(sequence_length))
    return apply_op("rnn", fn, (x, *flat_w, *states))


def reindex_graph(x, neighbors, count, hashtable_value=None,
                  hashtable_index=None, name=None):
    """Parity: reference reindex_graph op — compress the union of seed
    nodes and neighbor ids to consecutive local ids."""
    def fn(xv, nb, cnt):
        xv = xv.reshape(-1).astype(jnp.int64)
        nb = nb.reshape(-1).astype(jnp.int64)
        allv = jnp.concatenate([xv, nb])
        # first-occurrence order: seeds get 0..len(x)-1, then new
        # neighbor ids in appearance order — matches the reference's
        # hashtable insertion semantics
        uniq, inv = jnp.unique(allv, return_inverse=True,
                               size=allv.shape[0], fill_value=-1)
        # rank unique ids by first occurrence
        first_pos = jnp.full((uniq.shape[0],), allv.shape[0],
                             jnp.int32).at[inv].min(
            jnp.arange(allv.shape[0], dtype=jnp.int32))
        order = jnp.argsort(first_pos, stable=True)
        rank = jnp.argsort(order, stable=True)
        remap = rank[inv]
        n_seed = xv.shape[0]
        reindex_src = remap[n_seed:]
        # dst: seed i repeated count[i] times
        seed_ids = jnp.repeat(jnp.arange(n_seed), cnt.reshape(-1),
                              total_repeat_length=nb.shape[0])
        out_nodes = uniq[order]
        return reindex_src.astype(jnp.int64), \
            seed_ids.astype(jnp.int64), out_nodes
    return apply_op("reindex_graph", fn,
                    (x, targ(neighbors), targ(count)))


def weighted_sample_neighbors(row, colptr, edge_weight, x, eids=None,
                              sample_size=-1, return_eids=False,
                              name=None):
    """Parity: reference weighted_sample_neighbors op — weighted
    sampling without replacement via the Gumbel top-k trick, dense over
    the max degree (XLA-friendly fixed shapes)."""
    from .random import next_key
    def fn(rw, cp, ew, seeds):
        n_seed = seeds.shape[0]
        deg = cp[seeds + 1] - cp[seeds]
        max_deg = int(rw.shape[0])
        k = sample_size if sample_size > 0 else max_deg
        # dense [n_seed, max_deg] neighbor table
        offs = jnp.arange(max_deg)
        idx = cp[seeds][:, None] + offs[None, :]
        valid = offs[None, :] < deg[:, None]
        idx = jnp.clip(idx, 0, rw.shape[0] - 1)
        nbrs = rw[idx]
        w = ew[idx]
        gumbel = -jnp.log(-jnp.log(jax.random.uniform(
            next_key(), w.shape) + 1e-20) + 1e-20)
        keyv = jnp.where(valid, jnp.log(jnp.maximum(w, 1e-20)) + gumbel,
                         -jnp.inf)
        kk = min(k, max_deg)
        top_v, top_i = lax.top_k(keyv, kk)
        sel = jnp.take_along_axis(nbrs, top_i, axis=1)
        sel_ok = jnp.isfinite(top_v)
        out_count = jnp.minimum(deg, kk).astype(jnp.int32)
        flat = jnp.where(sel_ok, sel, -1).reshape(-1)
        return flat, out_count
    return apply_op("weighted_sample_neighbors", fn,
                    (row, targ(colptr), targ(edge_weight), targ(x)))


def _surface_entries():
    """(name, callable, category) rows registered onto the live registry."""
    from ..nn import functional as F
    from .. import fft as _fft
    from .. import metric as _metric
    from .. import geometric as _geo
    from ..text import viterbi_decode as _viterbi
    from ..vision import ops as _vops
    from ..incubate.nn import functional as IF
    from . import paged_attention as _paged

    rows = [
        # --- activations under reference op names
        ("logsigmoid", F.log_sigmoid, "activation"),
        ("tanh_shrink", F.tanhshrink, "activation"),
        # --- nn functional ops
        ("dropout", F.dropout, "nn"),
        ("embedding", F.embedding, "nn"),
        ("bilinear", F.bilinear, "nn"),
        ("fold", F.fold, "nn"),
        ("batch_norm", F.batch_norm, "norm"),
        ("layer_norm", F.layer_norm, "norm"),
        ("instance_norm", F.instance_norm, "norm"),
        ("group_norm", F.group_norm, "norm"),
        ("rms_norm", F.rms_norm, "norm"),
        ("sync_batch_norm_", F.batch_norm, "norm"),
        ("conv2d", F.conv2d, "conv"),
        ("conv3d", F.conv3d, "conv"),
        ("conv2d_transpose", F.conv2d_transpose, "conv"),
        ("conv3d_transpose", F.conv3d_transpose, "conv"),
        ("depthwise_conv2d", depthwise_conv2d, "conv"),
        ("depthwise_conv2d_transpose", depthwise_conv2d_transpose,
         "conv"),
        ("affine_grid", F.affine_grid, "vision"),
        ("grid_sample", F.grid_sample, "vision"),
        ("channel_shuffle", F.channel_shuffle, "vision"),
        ("temporal_shift", F.temporal_shift, "vision"),
        ("pixel_shuffle", F.pixel_shuffle, "vision"),
        ("pixel_unshuffle", F.pixel_unshuffle, "vision"),
        ("nearest_interp", _make_interp("nearest"), "vision"),
        ("bilinear_interp", _make_interp("bilinear"), "vision"),
        ("bicubic_interp", _make_interp("bicubic"), "vision"),
        ("trilinear_interp", _make_interp("trilinear"), "vision"),
        ("linear_interp", _make_interp("linear"), "vision"),
        ("pool2d", pool2d, "pooling"),
        ("pool3d", pool3d, "pooling"),
        ("max_pool2d_v2", pool2d, "pooling"),
        ("max_pool2d_with_index", max_pool2d_with_index, "pooling"),
        ("max_pool3d_with_index", max_pool3d_with_index, "pooling"),
        ("fractional_max_pool2d", F.fractional_max_pool2d, "pooling"),
        ("unpool", F.max_unpool2d, "pooling"),
        ("unpool3d", F.max_unpool3d, "pooling"),
        # --- losses
        ("bce_loss", bce_loss, "loss"),
        ("sigmoid_cross_entropy_with_logits",
         sigmoid_cross_entropy_with_logits, "loss"),
        ("huber_loss", huber_loss, "loss"),
        ("kldiv_loss", F.kl_div, "loss"),
        ("nll_loss", F.nll_loss, "loss"),
        ("log_loss", F.log_loss, "loss"),
        ("cross_entropy_with_softmax", cross_entropy_with_softmax,
         "loss"),
        ("margin_cross_entropy", F.margin_cross_entropy, "loss"),
        ("warpctc", F.ctc_loss, "loss"),
        ("identity_loss", identity_loss, "loss"),
        # --- tensor misc
        ("p_norm", p_norm, "math"),
        ("frobenius_norm", frobenius_norm, "math"),
        ("mean_all", mean_all, "reduction"),
        ("squared_l2_norm", squared_l2_norm, "math"),
        ("clip_by_norm", clip_by_norm, "math"),
        ("fill_diagonal", fill_diagonal, "manipulation"),
        ("fill_diagonal_tensor", fill_diagonal_tensor, "manipulation"),
        ("sequence_mask", sequence_mask, "manipulation"),
        ("gather_tree", gather_tree, "manipulation"),
        ("edit_distance", edit_distance, "misc"),
        ("split_with_num", split_with_num, "manipulation"),
        ("elementwise_pow", elementwise_pow, "math"),
        ("shape", shape, "manipulation"),
        ("fill", fill, "creation"),
        ("full_batch_size_like", full_batch_size_like, "creation"),
        ("full_with_tensor", full_with_tensor, "creation"),
        ("repeat_interleave_with_tensor_index",
         repeat_interleave_with_tensor_index, "manipulation"),
        ("matrix_rank_tol", matrix_rank_tol, "linalg"),
        ("index_select_strided", index_select_strided, "manipulation"),
        ("view_shape", view_shape, "manipulation"),
        ("view_dtype", view_dtype, "manipulation"),
        ("tensor_unfold", tensor_unfold, "manipulation"),
        ("trans_layout", trans_layout, "manipulation"),
        ("copy_to", copy_to, "device"),
        ("check_numerics", check_numerics, "debug"),
        ("embedding_grad_dense", embedding_grad_dense, "nn"),
        ("accuracy", _metric.accuracy, "metric"),
        ("viterbi_decode", _viterbi, "text"),
        ("fc", fc, "nn"),
        ("nms", _vops.nms, "vision"),
        ("roi_align", _vops.roi_align, "vision"),
        ("roi_pool", _vops.roi_pool, "vision"),
        # --- graph / segment
        ("segment_pool", segment_pool, "geometric"),
        ("send_u_recv", _geo.send_u_recv, "geometric"),
        ("send_ue_recv", _geo.send_ue_recv, "geometric"),
        ("send_uv", _geo.send_uv, "geometric"),
        # --- fft (op-level names over the python API)
        ("fft_c2c", _fft.fftn, "fft"),
        ("fft_r2c", _fft.rfftn, "fft"),
        ("fft_c2r", _fft.irfftn, "fft"),
        # --- fused / attention ops
        ("flash_attn", F.flash_attention, "fused"),
        ("fused_dot_product_attention", F.scaled_dot_product_attention,
         "fused"),
        ("self_dp_attention", F.scaled_dot_product_attention, "fused"),
        ("memory_efficient_attention", F.scaled_dot_product_attention,
         "fused"),
        ("fused_softmax_mask_upper_triangle",
         fused_softmax_mask_upper_triangle, "fused"),
        ("skip_layernorm", skip_layernorm, "fused"),
        ("fused_bias_residual_layernorm", fused_bias_residual_layernorm,
         "fused"),
        ("fused_batch_norm_act", fused_batch_norm_act, "fused"),
        ("fused_bn_add_activation", fused_bn_add_activation, "fused"),
        ("fused_conv2d_add_act", fused_conv2d_add_act, "fused"),
        ("fused_scale_bias_add_relu", fused_scale_bias_add_relu,
         "fused"),
        ("fused_fc_elementwise_layernorm",
         fused_fc_elementwise_layernorm, "fused"),
        ("fused_embedding_eltwise_layernorm",
         fused_embedding_eltwise_layernorm, "fused"),
        ("fused_linear_param_grad_add", fused_linear_param_grad_add,
         "fused"),
        ("multihead_matmul", multihead_matmul, "fused"),
        ("fused_bias_act", IF.fused_bias_act, "fused"),
        ("fused_dropout_add", IF.fused_dropout_add, "fused"),
        ("fused_bias_dropout_residual_layer_norm",
         IF.fused_bias_dropout_residual_layer_norm, "fused"),
        ("fused_rotary_position_embedding",
         IF.fused_rotary_position_embedding, "fused"),
        ("variable_length_memory_efficient_attention",
         IF.variable_length_memory_efficient_attention, "fused"),
        ("block_multihead_attention_", _paged.block_multihead_attention,
         "fused"),
        ("masked_multihead_attention_", _paged.masked_multihead_attention,
         "fused"),
        # --- quant
        ("weight_quantize", weight_quantize, "quant"),
        ("weight_dequantize", weight_dequantize, "quant"),
        ("weight_only_linear", weight_only_linear, "quant"),
        ("llm_int8_linear", llm_int8_linear, "quant"),
        # --- legacy comm ops
        ("c_identity", c_identity, "comm"),
        ("c_sync_calc_stream", c_sync_calc_stream, "comm"),
        ("c_sync_comm_stream", c_sync_comm_stream, "comm"),
    ]

    from .. import signal as _signal
    rows += [
        # --- plain-def activations under their reference op names
        ("softmax", F.softmax, "activation"),
        ("log_softmax", F.log_softmax, "activation"),
        ("gelu", F.gelu, "activation"),
        ("prelu", F.prelu, "activation"),
        ("rrelu", F.rrelu, "activation"),
        ("maxout", F.maxout, "activation"),
        ("gumbel_softmax", F.gumbel_softmax, "activation"),
        ("label_smooth", F.label_smooth, "activation"),
        ("celu", F.celu, "activation"),
        ("elu", F.elu, "activation"),
        ("selu", F.selu, "activation"),
        ("hardshrink", F.hardshrink, "activation"),
        ("hardsigmoid", F.hardsigmoid, "activation"),
        ("hardswish", F.hardswish, "activation"),
        ("hardtanh", F.hardtanh, "activation"),
        ("leaky_relu", F.leaky_relu, "activation"),
        ("softplus", F.softplus, "activation"),
        ("softshrink", F.softshrink, "activation"),
        ("swish", F.swish, "activation"),
        ("thresholded_relu", F.thresholded_relu, "activation"),
        # --- signal
        ("frame", _signal.frame, "signal"),
        ("overlap_add", _signal.overlap_add, "signal"),
        # --- padding / assignment / creation
        ("pad3d", pad3d, "nn"),
        ("set_value", set_value, "manipulation"),
        ("set_value_with_tensor", set_value_with_tensor, "manipulation"),
        ("full_", full_, "creation"),
        ("assign_out_", assign_out_, "creation"),
        ("assign_value_", assign_value_, "creation"),
        ("full_int_array", full_int_array, "creation"),
        ("data", data, "creation"),
        # --- random
        ("gaussian", gaussian, "random"),
        ("gaussian_inplace", gaussian_inplace, "random"),
        ("uniform_inplace", uniform_inplace, "random"),
        ("truncated_gaussian_random", truncated_gaussian_random,
         "random"),
        ("standard_gamma", standard_gamma, "random"),
        ("dirichlet", dirichlet, "random"),
        ("binomial", binomial, "random"),
        # --- debug toggles / metrics
        ("enable_check_model_nan_inf", enable_check_model_nan_inf,
         "debug"),
        ("disable_check_model_nan_inf", disable_check_model_nan_inf,
         "debug"),
        ("auc", auc, "metric"),
        # --- norm / attention tail
        ("spectral_norm", spectral_norm, "norm"),
        ("flash_attn_unpadded", flash_attn_unpadded, "fused"),
        ("fractional_max_pool3d", fractional_max_pool3d, "pooling"),
        ("squeeze_excitation_block", squeeze_excitation_block, "fused"),
        ("fused_scale_bias_relu_conv_bn", fused_scale_bias_relu_conv_bn,
         "fused"),
        ("fused_dconv_drelu_dbn", fused_dconv_drelu_dbn, "fused"),
        # --- io
        ("decode_jpeg", decode_jpeg, "vision"),
        ("read_file", read_file, "vision"),
        # --- remaining real implementations
        ("warprnnt", warprnnt, "loss"),
        ("hsigmoid_loss", hsigmoid_loss, "loss"),
        ("class_center_sample", class_center_sample, "loss"),
        ("rnn", rnn, "nn"),
        ("reindex_graph", reindex_graph, "geometric"),
        ("weighted_sample_neighbors", weighted_sample_neighbors,
         "geometric"),
        ("coalesce_tensor", coalesce_tensor, "fused"),
    ]
    return rows


def register_framework_ops():
    """Register the reference-YAML surface (idempotent).  Subsystem
    imports are best-effort: a partially-built tree (the package init's
    _OPTIONAL_SUBMODULES contract) skips the dependent rows instead of
    breaking `import paddle_tpu`."""
    try:
        entries = _surface_entries()
    except ModuleNotFoundError:  # pragma: no cover - bring-up only
        entries = []
    for name, fn, cat in entries:
        _reg(name, fn, cat)
    from .optim_ops import register_optim_ops
    register_optim_ops()
    try:
        from ..vision.detection import register_detection_ops
        register_detection_ops()
    except ModuleNotFoundError:  # pragma: no cover - bring-up only
        pass
    # comm ops that need the collective module (import late: distributed
    # pulls in topology etc.)
    try:
        from ..distributed import collective as C

        def c_allgather(x, ring_id=0, nranks=1, use_calc_stream=True,
                        name=None):
            """Parity: reference c_allgather op."""
            return C.all_gather_concat(x) if hasattr(
                C, "all_gather_concat") else C.all_gather(x)

        def c_allreduce_sum(x, ring_id=0, use_calc_stream=True,
                            use_model_parallel=False, name=None):
            """Parity: reference c_allreduce_sum op."""
            return C.all_reduce(x)

        def c_allreduce_max(x, ring_id=0, use_calc_stream=True,
                            use_model_parallel=False, name=None):
            """Parity: reference c_allreduce_max op."""
            return C.all_reduce(x, op=C.ReduceOp.MAX if hasattr(
                C, "ReduceOp") else "max")

        def c_broadcast(x, root=0, ring_id=0, use_calc_stream=True,
                        name=None):
            """Parity: reference c_broadcast op."""
            return C.broadcast(x, root)

        def c_reduce_sum(x, root_id=0, ring_id=0, use_calc_stream=True,
                         name=None):
            """Parity: reference c_reduce_sum op."""
            return C.reduce(x, root_id)

        def c_concat(x, rank=0, nranks=1, ring_id=0, use_calc_stream=True,
                     use_model_parallel=True, name=None):
            """Parity: reference c_concat op (allgather + concat on the
            last axis — the mp row-parallel output transition)."""
            return C.all_gather_concat(x, axis=-1) if hasattr(
                C, "all_gather_concat") else C.all_gather(x)

        def c_embedding(weight, x, start_index=0, vocab_size=-1,
                        name=None):
            """Parity: reference c_embedding op (vocab-parallel shard
            lookup: ids outside [start, start+rows) contribute zeros)."""
            def fn(w, ids):
                local = ids - start_index
                ok = (local >= 0) & (local < w.shape[0])
                safe = jnp.clip(local, 0, w.shape[0] - 1)
                out = w[safe]
                return jnp.where(ok[..., None], out, 0).astype(w.dtype)
            return apply_op("c_embedding", fn, (weight, targ(x)))

        for nm, f in [("c_allgather", c_allgather),
                      ("c_allreduce_sum", c_allreduce_sum),
                      ("c_allreduce_max", c_allreduce_max),
                      ("c_broadcast", c_broadcast),
                      ("c_reduce_sum", c_reduce_sum),
                      ("c_concat", c_concat),
                      ("c_embedding", c_embedding)]:
            _reg(nm, f, "comm")
    except Exception:  # pragma: no cover - distributed not built
        pass
