"""Tensor-API long tail: the remaining reference paddle.tensor surface.

Parity: python/paddle/tensor/__init__.py export list (reference) — the 38
names absent after the core op families; each lowers to one or a few XLA
ops through apply_op so forward AND vjp come from the same definition.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy import special as jspecial

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .registry import register_op, register
from ._helpers import as_value, wrap, targ, def_unary, def_binary


# ---------------------------------------------------------------------------
# shape / structure
# ---------------------------------------------------------------------------
def broadcast_shape(x_shape, y_shape):
    """Parity: paddle.broadcast_shape — pure shape computation."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@register_op("rank", category="manipulation")
def rank(input, name=None):
    return wrap(jnp.asarray(as_value(input).ndim, jnp.int32))


@register_op("tensor_split", category="manipulation", tensor_method=True)
def tensor_split(x, num_or_indices, axis=0, name=None):
    v = as_value(x)
    if isinstance(num_or_indices, int):
        parts = np.array_split(np.arange(v.shape[axis]), num_or_indices)
        idx = np.cumsum([len(p) for p in parts])[:-1].tolist()
    else:
        idx = list(num_or_indices)
    outs = jnp.split(v, idx, axis=axis)
    return [wrap(o) for o in outs]


@register_op("hsplit", category="manipulation", tensor_method=True)
def hsplit(x, num_or_indices, name=None):
    ax = 0 if as_value(x).ndim == 1 else 1
    return tensor_split(x, num_or_indices, axis=ax)


@register_op("vsplit", category="manipulation", tensor_method=True)
def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


@register_op("dsplit", category="manipulation", tensor_method=True)
def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


@register_op("unflatten", category="manipulation", tensor_method=True)
def unflatten(x, axis, shape, name=None):
    def fn(v):
        ax = axis % v.ndim
        shp = [int(s.item()) if hasattr(s, "item") else int(s)
               for s in (shape if isinstance(shape, (list, tuple))
                         else list(np.asarray(as_value(shape))))]
        return v.reshape(v.shape[:ax] + tuple(shp) + v.shape[ax + 1:])
    return apply_op("unflatten", fn, (x,))


@register_op("unfold", category="manipulation", tensor_method=True)
def unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis`` (Tensor.unfold, reference
    python/paddle/tensor/manipulation.py tensor_unfold)."""
    from ._helpers import sliding_windows

    def fn(v):
        ax = axis % v.ndim
        return jnp.moveaxis(sliding_windows(v, ax, size, step), ax + 1, -1)
    return apply_op("unfold", fn, (x,))


@register_op("reverse", category="manipulation", tensor_method=True)
def reverse(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op("reverse", lambda v: jnp.flip(v, ax), (x,))


# -- scatter views ----------------------------------------------------------
@register_op("diagonal_scatter", category="manipulation", tensor_method=True)
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def fn(v, w):
        a1, a2 = axis1 % v.ndim, axis2 % v.ndim
        moved = jnp.moveaxis(v, (a1, a2), (-2, -1))
        n, m = moved.shape[-2], moved.shape[-1]
        if offset >= 0:
            L = min(n, m - offset)
            r, c = np.arange(L), np.arange(L) + offset
        else:
            L = min(n + offset, m)
            r, c = np.arange(L) - offset, np.arange(L)
        out = moved.at[..., r, c].set(w)   # w: diagonal shape [..., L]
        return jnp.moveaxis(out, (-2, -1), (a1, a2))
    return apply_op("diagonal_scatter", fn, (x, targ(y)))


@register_op("select_scatter", category="manipulation", tensor_method=True)
def select_scatter(x, values, axis, index, name=None):
    def fn(v, w):
        idx = [slice(None)] * v.ndim
        idx[axis % v.ndim] = index
        return v.at[tuple(idx)].set(w)
    return apply_op("select_scatter", fn, (x, targ(values)))


@register_op("slice_scatter", category="manipulation", tensor_method=True)
def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def fn(v, w):
        idx = [slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax % v.ndim] = slice(int(st), int(en), int(sd))
        return v.at[tuple(idx)].set(w)
    return apply_op("slice_scatter", fn, (x, targ(value)))


@register_op("index_fill", category="manipulation", tensor_method=True,
             inplace_alias=True)
def index_fill(x, index, axis, value, name=None):
    def fn(v, idx):
        sl = [slice(None)] * v.ndim
        sl[axis % v.ndim] = idx
        val = value._value if isinstance(value, Tensor) else value
        return v.at[tuple(sl)].set(val)
    return apply_op("index_fill", fn, (x, as_value(index)))


def index_fill_(x, index, axis, value, name=None):
    return x._inplace_assign(index_fill(x, index, axis, value))


# ---------------------------------------------------------------------------
# math long tail
# ---------------------------------------------------------------------------
@register_op("cdist", category="math")
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def fn(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, -1) + 1e-30)
        if p == float("inf"):
            return jnp.max(jnp.abs(diff), -1)
        return jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)
    return apply_op("cdist", fn, (x, targ(y)))


@register_op("cumulative_trapezoid", category="math", tensor_method=True)
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None and dx is not None:
        raise ValueError(
            "cumulative_trapezoid: pass either x or dx, not both")

    def fn(yv, *rest):
        yl = jnp.moveaxis(yv, axis, -1)
        avg = (yl[..., 1:] + yl[..., :-1]) / 2.0
        if rest:
            xs = jnp.moveaxis(rest[0], axis, -1)
            widths = xs[..., 1:] - xs[..., :-1]
        else:
            widths = 1.0 if dx is None else dx
        return jnp.moveaxis(jnp.cumsum(avg * widths, -1), -1, axis)
    args = (y,) if x is None else (y, targ(x))
    return apply_op("cumulative_trapezoid", fn, args)


@register_op("frexp", category="math", tensor_method=True)
def frexp(x, name=None):
    v = as_value(x)
    m, e = jnp.frexp(v)
    return wrap(m), wrap(e.astype(v.dtype))


@register_op("increment", category="math")
def increment(x, value=1.0, name=None):
    return x._inplace_assign(apply_op("increment", lambda v: v + value,
                                      (x,)))


@register_op("polar", category="math")
def polar(abs, angle, name=None):
    return apply_op(
        "polar", lambda a, t: (a * jnp.cos(t)) + 1j * (a * jnp.sin(t)),
        (abs, targ(angle)))


@register_op("renorm", category="math", tensor_method=True,
             inplace_alias=True)
def renorm(x, p, axis, max_norm, name=None):
    def fn(v):
        ax = axis % v.ndim
        other = tuple(i for i in range(v.ndim) if i != ax)
        norms = jnp.sum(jnp.abs(v) ** p, axis=other, keepdims=True) \
            ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-12),
                           1.0)
        return v * factor
    return apply_op("renorm", fn, (x,))


@register_op("sgn", category="math", tensor_method=True)
def sgn(x, name=None):
    def fn(v):
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.where(mag == 0, 1, mag))
        return jnp.sign(v)
    return apply_op("sgn", fn, (x,))


@register_op("vander", category="math", tensor_method=True)
def vander(x, n=None, increasing=False, name=None):
    def fn(v):
        cols = v.shape[0] if n is None else n
        powers = jnp.arange(cols)
        if not increasing:
            powers = powers[::-1]
        return v[:, None] ** powers[None, :]
    return apply_op("vander", fn, (x,))


gammaln = def_unary("gammaln", jspecial.gammaln)


def gammaln_(x, name=None):
    return x._inplace_assign(gammaln(x))


@register_op("multigammaln", category="math", tensor_method=True,
             inplace_alias=True)
def multigammaln(x, p, name=None):
    return apply_op("multigammaln",
                    lambda v: jspecial.multigammaln(v, p), (x,))


def multigammaln_(x, p, name=None):
    return x._inplace_assign(multigammaln(x, p))


# dtype predicates ----------------------------------------------------------
def is_complex(x):
    return bool(jnp.issubdtype(as_value(x).dtype, jnp.complexfloating))


def is_floating_point(x):
    return bool(jnp.issubdtype(as_value(x).dtype, jnp.floating))


def is_integer(x):
    return bool(jnp.issubdtype(as_value(x).dtype, jnp.integer))


# ---------------------------------------------------------------------------
# creation / random
# ---------------------------------------------------------------------------
def create_tensor(dtype, name=None, persistable=False):
    return Tensor(jnp.zeros((), _dt.convert_dtype(dtype)))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.layer_base import Parameter
    from ..nn import initializer as I
    init = default_initializer or (I.Constant(0.0) if is_bias
                                   else I.XavierNormal())
    d = _dt.convert_dtype(dtype)
    t = Parameter(init(tuple(shape), d))
    t.stop_gradient = False
    return t


@register_op("cauchy_", category="random")
def cauchy_(x, loc=0, scale=1, name=None):
    from .random import next_key
    v = as_value(x)
    u = jax.random.uniform(next_key(), v.shape, jnp.float32, 1e-7,
                           1 - 1e-7)
    x._value = (loc + scale * jnp.tan(jnp.pi * (u - 0.5))).astype(v.dtype)
    return x


@register_op("geometric_", category="random")
def geometric_(x, probs, name=None):
    from .random import next_key
    v = as_value(x)
    p = as_value(probs)
    u = jax.random.uniform(next_key(), v.shape, jnp.float32, 1e-7,
                           1 - 1e-7)
    x._value = jnp.ceil(jnp.log(u) / jnp.log1p(-p)).astype(v.dtype)
    return x


# ---------------------------------------------------------------------------
# sampling (serving path)
# ---------------------------------------------------------------------------
@register_op("top_p_sampling", category="random")
def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling (parity: paddle.tensor.top_p_sampling; reference
    paddle/phi/kernels/gpu/top_p_sampling_kernel.cu capability).

    x: [batch, vocab] probabilities; ps: [batch] cumulative-probability
    cutoffs.  Returns (sampled probability, sampled ids), both [batch, 1].
    """
    from .random import next_key, _seeded_key
    v = as_value(x)
    p = as_value(ps).reshape(-1)
    key = _seeded_key(seed) if seed not in (None, -1) else next_key()

    order = jnp.argsort(-v, axis=-1)
    sorted_probs = jnp.take_along_axis(v, order, -1)
    cum = jnp.cumsum(sorted_probs, -1)
    keep = cum - sorted_probs <= p[:, None]   # always keep the top token
    masked = jnp.where(keep, sorted_probs, 0.0)
    masked = masked / jnp.sum(masked, -1, keepdims=True)
    g = jax.random.gumbel(key, masked.shape)
    choice = jnp.argmax(jnp.where(keep, jnp.log(masked + 1e-30) + g,
                                  -jnp.inf), -1)
    ids = jnp.take_along_axis(order, choice[:, None], -1)
    probs = jnp.take_along_axis(v, ids, -1)
    return wrap(probs), wrap(ids.astype(jnp.int64))


@register_op("combinations", category="math", tensor_method=True)
def combinations(x, r=2, with_replacement=False, name=None):
    """Parity: python/paddle/tensor/math.py:7446 — itertools-style
    length-r combinations of a 1-D tensor, index pattern computed at
    trace time (static shape), values gathered in one op."""
    import itertools as _it

    def fn(v):
        n = v.shape[0]
        gen = _it.combinations_with_replacement(range(n), r) \
            if with_replacement else _it.combinations(range(n), r)
        idx = np.asarray(list(gen), dtype=np.int32).reshape(-1, r)
        return v[jnp.asarray(idx)]

    return apply_op("combinations", fn, (x,))
