"""Paged (block) attention for serving.

Parity: the reference's LLM-serving fused kernels
(paddle/phi/kernels/fusion/block_multihead_attention_kernel.cu — paged KV
cache addressed through per-sequence block tables — and
masked_multihead_attention for dense-cache decode).

TPU-native design: the KV cache is a pool of fixed-size pages
``[num_blocks, block_size, kv_heads, head_dim]`` living in HBM; a batch
addresses it through ``block_tables [B, max_blocks]``.  Decode attention
runs as a Pallas kernel — grid over (batch, kv_head), the page list is a
scalar-prefetch operand, and pages are DMA'd HBM→VMEM with online-softmax
accumulation — so one query token never materializes the gathered
[L, D] cache in HBM.  An XLA gather fallback covers CPU and is the
numerics reference in tests.
"""
from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..core.tensor import Tensor
from .online_softmax import merge_partials, online_softmax_update

__all__ = ["PagedKVCache", "KVPageBuffer",
           "paged_attention", "write_kv_to_cache",
           "write_decode_kv", "write_prefill_kv", "write_chunk_kv",
           "write_ragged_kv", "chunk_prefill_attention",
           "chunk_prefill_attention_partial",
           "ragged_paged_attention",
           "write_decode_kv_q8", "write_chunk_kv_q8",
           "write_ragged_kv_q8", "dequant_pages",
           "reconstruct_kv", "block_multihead_attention",
           "masked_multihead_attention"]

# symmetric int8 bound == quantization.functional.symmetric_bound(8).
# The quant/dequant math itself routes through that module (the ONE
# clamp implementation); this constant exists only for the in-kernel
# scale folds in the Pallas paths, where the float literal must be a
# trace-time static (contract locked by tests/test_serving_quant.py).
_KV_BNT = 127.0

# Round-17 declared tolerance (r13 convention: int8 paths are
# tolerance-gated, never byte-gated) for the int8 MXU kernels vs the
# dequantizing XLA reference: the pipelined kernels quantize the q rows
# to int8 in-kernel (per-row absmax), so scores pick up one extra
# quantization (<= q_absmax/254 per element before the dot) the
# reference doesn't have.  The bound is RELATIVE to the pool's
# dequantized value magnitude because attention outputs are convex
# combinations of V rows — measured max deviation on the parity sweep
# is ~5e-3 at unit-variance data; 0.02 carries ~4x headroom.  Validity
# regime: the q-quant error perturbs the SOFTMAX EXPONENT by up to
# softmax_scale * (q_absmax/254) * sum|k_row| per score, so the bound
# holds while that perturbation stays well under 1 (K magnitudes up to
# a few tens at D=16..128 — comfortably covering rope'd projection
# outputs); beyond it, softmax exponentiation amplifies without bound
# and the meaningful gate is the engine-level token-match rate, not a
# tensor atol.  The legacy (pipelined=False) kernels keep the r13
# dequant math and stay within 1e-5 of the reference.
KERNEL_INT8_REL_TOL = 0.02


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# page-migration wire format (round 19)
# ---------------------------------------------------------------------------
@dataclass
class KVPageBuffer:
    """A sequence's physical KV pages serialized to host RAM — the unit
    both page MIGRATION (engine → engine) and the host-RAM prefix-cache
    spill tier move around.

    Wire format: ``codes`` is ONE contiguous host array
    ``[2*num_layers, n_pages, block_size, num_kv_heads, head_dim]`` in
    the pool dtype — rows ``0..L-1`` are the K pages of layers
    ``0..L-1``, rows ``L..2L-1`` the V pages (the per-layer extents).
    An int8 pool additionally carries its per-page-per-head fp32 absmax
    rows as ``scales [2L, n_pages, num_kv_heads]`` in the same layer
    order — scales live per PHYSICAL page, so they travel with their
    pages for free and an injected page dequantizes bit-identically to
    its source.  The header fields pin the pool geometry; ``inject``
    into a pool with a different geometry (including a different
    ``kv_dtype``) is rejected with a construction-time ValueError, never
    a shape failure inside a trace.

    ``n_tokens`` records how many tokens of KV the pages actually cover
    (the last page may be partial) — the resume seq_len on the target
    engine."""
    codes: np.ndarray
    scales: Optional[np.ndarray]
    n_pages: int
    n_tokens: int
    block_size: int
    num_kv_heads: int
    head_dim: int
    num_layers: int
    kv_dtype: str

    @property
    def nbytes(self) -> int:
        return int(self.codes.nbytes
                   + (self.scales.nbytes if self.scales is not None
                      else 0))

    def geometry(self) -> tuple:
        return (self.num_layers, self.block_size, self.num_kv_heads,
                self.head_dim, self.kv_dtype)


# ---------------------------------------------------------------------------
# cache pool management (host-side; the reference keeps this in the
# serving runtime around the kernel too)
# ---------------------------------------------------------------------------
class PagedKVCache:
    """A pool of KV pages plus a per-layer free-list/block-table manager.

    One instance serves one transformer layer.  Arrays are jax arrays so
    updates stay on device; the free list is host state (allocation is
    control flow, not compute).

    Pages are REFCOUNTED: ``allocate_block`` hands out a page with one
    reference, ``share_blocks`` adds references (prefix caching — two
    requests whose prompts share a prefix address the same physical
    pages), and ``free_sequence`` is the single release path: it drops
    one reference per page and only returns a page to the free list
    when its count reaches zero.  A page shared by a prefix-cache table
    or another live request's block table therefore survives any one
    holder finishing (including pool-dry victim truncation and
    lazy-alloc growth — both funnel through ``free_sequence``).

    ``kv_dtype="int8"`` quantizes the pools: K/V pages store symmetric
    int8 codes plus per-PAGE-per-HEAD fp32 absmax scales
    (``key_scale``/``value_scale`` [phys, Hkv]) — ~4× (vs fp32) /
    ~2× (vs bf16) pages per HBM byte, scales included in the byte
    accounting.  Every compiled write path (``write_*_kv_q8``)
    quantizes on write with a running-max scale (existing codes are
    rescaled in the same dispatch when a new token raises a page's
    absmax), every attention path dequantizes into the same fp32
    online-softmax, and because scales live per PHYSICAL page, prefix
    sharing (``share_blocks``), copy-on-write (``serving_step.
    copy_block`` copies the scale row with the page) and refcounted
    release all carry scales with their pages for free.
    """

    def __init__(self, num_blocks: int, block_size: int, num_kv_heads: int,
                 head_dim: int, dtype=jnp.float32, sink_block: bool = False,
                 kv_dtype: Optional[str] = None):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        if kv_dtype not in (None, "float32", "bfloat16", "int8"):
            raise ValueError(
                "PagedKVCache kv_dtype must be one of None (use dtype), "
                "'float32', 'bfloat16' or 'int8'; got %r" % (kv_dtype,))
        self.quantized = kv_dtype == "int8"
        if kv_dtype is not None:
            dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                     "int8": jnp.int8}[kv_dtype]
        self.kv_dtype = jnp.dtype(dtype).name
        # sink_block=True adds ONE extra physical page, never in the free
        # list, exposed as .sink: a fixed-shape compiled decode step
        # routes the writes of inactive (masked) batch slots there, so
        # slot occupancy changes never corrupt live pages and never
        # change any traced shape.
        self.sink = num_blocks if sink_block else -1
        phys = num_blocks + (1 if sink_block else 0)
        shape = (phys, block_size, num_kv_heads, head_dim)
        self.key_cache = jnp.zeros(shape, dtype)
        self.value_cache = jnp.zeros(shape, dtype)
        if self.quantized:
            # per-page-per-head absmax; 0 = "nothing written yet" (the
            # quantized writes grow it monotonically per page lifetime)
            self.key_scale = jnp.zeros((phys, num_kv_heads), jnp.float32)
            self.value_scale = jnp.zeros((phys, num_kv_heads),
                                         jnp.float32)
        else:
            self.key_scale = None
            self.value_scale = None
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref: dict = {}            # block id -> live reference count

    def place(self, sharding, scale_sharding=None):
        """Place both pools with a ``NamedSharding`` — the
        tensor-parallel serving engine head-shards them
        (``P(None, None, 'tp', None)``): each chip physically holds
        only its kv-head slice of every page, so per-chip pool HBM is
        exactly 1/tp.  A quantized pool's scale tables follow with
        ``scale_sharding`` (head axis: ``P(None, 'tp')``).  Free-list/
        refcount state is host bookkeeping and needs no placement.
        Call once at engine construction, before any compiled step
        consumes (donates) the arrays."""
        self.key_cache = jax.device_put(self.key_cache, sharding)
        self.value_cache = jax.device_put(self.value_cache, sharding)
        if self.quantized and scale_sharding is not None:
            self.key_scale = jax.device_put(self.key_scale,
                                            scale_sharding)
            self.value_scale = jax.device_put(self.value_scale,
                                              scale_sharding)

    def per_chip_pool_bytes(self) -> int:
        """Bytes of ONE chip's shard of this layer's K+V pools (the
        whole pool when unsharded) — the capacity number the
        multi-chip serving bench gates at ≈ pool/tp, and the
        quantization bench gates at ≥1.9× pages per HBM byte.  A
        quantized pool COUNTS ITS SCALE TABLES, so the capacity claim
        stays honest."""
        total = 0
        arrs = [self.key_cache, self.value_cache]
        if self.quantized:
            arrs += [self.key_scale, self.value_scale]
        for arr in arrs:
            shape = arr.sharding.shard_shape(arr.shape) \
                if getattr(arr, "sharding", None) is not None \
                else arr.shape
            total += int(np.prod(shape)) * arr.dtype.itemsize
        return total

    def page_geometry(self) -> tuple:
        """One layer-pool's page geometry ``(block_size, num_kv_heads,
        head_dim, kv_dtype)`` — the per-layer part of the migration
        wire-format header (``KVPageBuffer`` adds the layer count)."""
        return (self.block_size, self.num_kv_heads, self.head_dim,
                self.kv_dtype)

    def allocate_block(self) -> int:
        if not self._free:
            raise RuntimeError(
                "PagedKVCache out of blocks (%d in pool); raise num_blocks "
                "or free finished sequences" % self.num_blocks)
        b = self._free.pop()
        self._ref[b] = 1
        return b

    def share_blocks(self, block_ids):
        """Add one reference to each page (prefix sharing)."""
        for b in block_ids:
            b = int(b)
            if b < 0 or b == self.sink:
                continue
            if b not in self._ref:
                raise RuntimeError(
                    "share_blocks(%d): page is not allocated" % b)
            self._ref[b] += 1

    def refcount(self, block_id: int) -> int:
        return self._ref.get(int(block_id), 0)

    def free_sequence(self, block_ids):
        """Drop one reference per page; recycle pages that hit zero.
        The ONLY release path — every finish/truncate/evict goes
        through here, so a shared page is never recycled while another
        holder's block table still references it."""
        for b in block_ids:
            b = int(b)
            if b < 0 or b == self.sink:
                continue
            n = self._ref.pop(b, 1) - 1
            if n > 0:
                self._ref[b] = n
            else:
                self._free.append(b)

    def blocks_needed(self, seq_len: int) -> int:
        return -(-seq_len // self.block_size)

    def trim_blocks(self, block_ids, n_tokens: int):
        """Speculative-decode rollback: release the TAIL pages past
        what ``n_tokens`` needs (pages grown for draft positions the
        verifier rejected) through the refcounted release path, and
        return the kept prefix.  A trimmed page shared with the prefix
        table or another request survives, exactly like any other
        ``free_sequence`` drop."""
        keep = self.blocks_needed(max(int(n_tokens), 1))
        if keep >= len(block_ids):
            return list(block_ids)
        self.free_sequence(block_ids[keep:])
        return list(block_ids[:keep])

    def build_block_table(self, seq_lens, max_blocks=None) -> np.ndarray:
        """Allocate pages for new sequences; returns [B, max_blocks]
        int32 table (-1 padded)."""
        tables = []
        for L in seq_lens:
            n = self.blocks_needed(max(int(L), 1))
            tables.append([self.allocate_block() for _ in range(n)])
        width = max_blocks or max(len(t) for t in tables)
        out = np.full((len(tables), width), -1, np.int32)
        for i, t in enumerate(tables):
            out[i, :len(t)] = t
        return out

    def append(self, k_new, v_new, block_tables, seq_lens):
        """Donating in-place append: updates self.key_cache/value_cache
        (the old buffers are consumed — use this, not the functional
        write_kv_to_cache, when the pool object owns the arrays)."""
        if self.quantized:
            raise NotImplementedError(
                "PagedKVCache.append is the legacy dense-cache API and "
                "does not quantize; an int8 pool must be written through "
                "the compiled serving steps (write_decode_kv_q8 / "
                "write_chunk_kv_q8 / write_ragged_kv_q8)")
        self.key_cache, self.value_cache = _write_decode_donated(
            _val(k_new), _val(v_new), self.key_cache, self.value_cache,
            jnp.asarray(np.asarray(block_tables), jnp.int32),
            jnp.asarray(np.asarray(seq_lens), jnp.int32))

    def ensure_capacity(self, block_tables: np.ndarray,
                        seq_lens) -> np.ndarray:
        """Grow tables so every sequence can hold seq_len+1 tokens."""
        bt = np.asarray(block_tables).copy()
        for i, L in enumerate(np.asarray(seq_lens)):
            need = self.blocks_needed(int(L) + 1)
            have = int((bt[i] >= 0).sum())
            while have < need:
                if (bt[i] >= 0).sum() == bt.shape[1]:
                    bt = np.concatenate(
                        [bt, np.full((bt.shape[0], 1), -1, np.int32)], 1)
                bt[i, have] = self.allocate_block()
                have += 1
        return bt


# ---------------------------------------------------------------------------
# cache write (scatter one new token per sequence)
# ---------------------------------------------------------------------------
def _write_decode_impl(k_new, v_new, key_cache, value_cache, block_tables,
                       seq_lens):
    """k_new/v_new [B, Hkv, D]; writes at position seq_lens[b]."""
    bs = key_cache.shape[1]
    pos = seq_lens.astype(jnp.int32)
    blk = jnp.take_along_axis(block_tables, (pos // bs)[:, None],
                              axis=1)[:, 0]
    off = pos % bs
    key_cache = key_cache.at[blk, off].set(k_new)
    value_cache = value_cache.at[blk, off].set(v_new)
    return key_cache, value_cache


# functional API: callers keep ownership of all buffers (no donation);
# PagedKVCache.append is the donating variant that rebinds its own state
_write_decode = jax.jit(_write_decode_impl)
_write_decode_donated = jax.jit(_write_decode_impl, donate_argnums=(2, 3))


def _write_prefill_impl(k_new, v_new, key_cache, value_cache, block_tables,
                        seq_lens):
    """k_new/v_new [B, S, Hkv, D]: one vectorized scatter for the whole
    prompt (not S sequential dispatches)."""
    B, S = k_new.shape[:2]
    bs = key_cache.shape[1]
    pos = seq_lens[:, None].astype(jnp.int32) + jnp.arange(
        S, dtype=jnp.int32)[None, :]                      # [B, S]
    blk = jnp.take_along_axis(block_tables, pos // bs, axis=1)  # [B, S]
    off = pos % bs
    key_cache = key_cache.at[blk, off].set(k_new)
    value_cache = value_cache.at[blk, off].set(v_new)
    return key_cache, value_cache


_write_prefill = jax.jit(_write_prefill_impl)
_write_prefill_donated = jax.jit(_write_prefill_impl, donate_argnums=(2, 3))

# traceable (un-jitted) functional appends: COMPOSE these under an outer
# jax.jit (the serving engine's single fused decode step) — calling the
# jitted variants from inside a trace would nest dispatches instead of
# fusing the scatter into the surrounding module
write_decode_kv = _write_decode_impl
write_prefill_kv = _write_prefill_impl


def write_chunk_kv(k_new, v_new, key_cache, value_cache, block_table_row,
                   start, n_valid, sink):
    """Scatter one PADDED prefill chunk into cache pages (traceable —
    composed inside the bucketed ``PrefillStep`` trace).

    k_new/v_new: [1, C, Hkv, D] where C is the bucket width; only the
    first ``n_valid`` positions carry real tokens.  Position i lands at
    sequence position ``start + i``; padded positions (i >= n_valid)
    are routed to the ``sink`` page so one compile per bucket serves
    every prompt length that rounds up to it without corrupting live
    pages.  start/n_valid are traced scalars: chunk offset and fill
    level never retrace.
    """
    C = k_new.shape[1]
    bs = key_cache.shape[1]
    idx = jnp.arange(C, dtype=jnp.int32)
    pos = start.astype(jnp.int32) + idx                      # [C]
    # OOB pos//bs for the padded tail clamps in the gather, then the
    # where() routes those writes to the sink page anyway
    blk = block_table_row[0, pos // bs]                      # [C]
    valid = idx < n_valid
    blk = jnp.where(valid, blk, jnp.int32(sink))
    off = jnp.where(valid, pos % bs, 0)
    key_cache = key_cache.at[blk, off].set(k_new[0])
    value_cache = value_cache.at[blk, off].set(v_new[0])
    return key_cache, value_cache


def chunk_prefill_attention(q, key_cache, value_cache, block_table_row,
                            start, scale, key_scale=None,
                            value_scale=None):
    """Causal attention for one padded prefill chunk over the paged
    cache (traceable; the bucketed ``PrefillStep``'s attention body).

    q: [1, C, H, D] — chunk queries at global positions start..start+C-1
    (the chunk's own K/V must already be written to the pages).  Masks
    keys to ``kpos <= qpos``, so chunk offset stays a traced scalar: one
    compile per bucket covers every chunk position, every prompt length
    in the bucket, and every prefix-cache suffix offset.  Padded queries
    produce garbage rows the caller never reads (the sampled token comes
    from position n_valid-1).

    The page loop is CLAMPED to the chunk's used block count
    ``ceil((start + C) / block_size)`` — a traced loop bound, so a short
    sequence in a large pool pays attention FLOPs proportional to its
    own fill, not the full table width.  Numerics: the row max is exact
    over the used window (identical to the full-width masked max, since
    every clamped-away key was -inf there), then the normalizer and the
    weighted sum accumulate page by page in position order.
    """
    B, C, H, D = q.shape
    Hkv = key_cache.shape[2]
    bs = key_cache.shape[1]
    W = int(block_table_row.shape[1])
    rep = H // Hkv
    qf = q[0].astype(jnp.float32) * jnp.float32(scale)   # [C, H, D]
    qpos = start.astype(jnp.int32) + jnp.arange(C, dtype=jnp.int32)
    n_used = jnp.minimum(
        (start.astype(jnp.int32) + C + bs - 1) // bs, jnp.int32(W))
    bt = jnp.maximum(block_table_row[0].astype(jnp.int32), 0)

    def page_scores(p_idx, k):
        # k [bs, H, D] (GQA-repeated) -> scores [H, C, bs], causal-masked
        s = jnp.einsum("qhd,khd->hqk", qf, k)
        cols = p_idx * bs + jnp.arange(bs, dtype=jnp.int32)
        ok = cols[None, None, :] <= qpos[None, :, None]
        return jnp.where(ok, s, -jnp.inf)

    def gather(p_idx, cache, cache_scale):
        page = cache[bt[p_idx]]                          # [bs, Hkv, D]
        if cache_scale is not None:
            page = dequant_pages(page, cache_scale[bt[p_idx]])
        else:
            page = page.astype(jnp.float32)
        if rep != 1:
            page = jnp.repeat(page, rep, axis=1)
        return page

    def max_body(p_idx, m):
        s = page_scores(p_idx, gather(p_idx, key_cache, key_scale))
        return jnp.maximum(m, jnp.max(s, axis=-1))

    m = jax.lax.fori_loop(jnp.int32(0), n_used, max_body,
                          jnp.full((H, C), -jnp.inf, jnp.float32))

    def acc_body(p_idx, carry):
        l, acc = carry
        s = page_scores(p_idx, gather(p_idx, key_cache, key_scale))
        p = jnp.exp(s - m[:, :, None])                   # -inf keys -> 0
        l = l + jnp.sum(p, axis=-1)
        acc = acc + jnp.einsum("hqk,khd->qhd", p,
                               gather(p_idx, value_cache, value_scale))
        return l, acc

    l, acc = jax.lax.fori_loop(
        jnp.int32(0), n_used, acc_body,
        (jnp.zeros((H, C), jnp.float32),
         jnp.zeros((C, H, D), jnp.float32)))
    out = acc / jnp.maximum(l, 1e-30).T[:, :, None]
    return out[None].astype(q.dtype)


def write_ragged_kv(k_new, v_new, key_cache, value_cache, dest_blocks,
                    dest_offsets):
    """Scatter a packed ragged token batch's K/V into cache pages
    (traceable — composed inside the fused ``MixedStep`` trace).

    k_new/v_new: [T, Hkv, D] — one row per packed token (decode slots
    and prefill-chunk tokens interleaved).  Token t lands at
    ``(dest_blocks[t], dest_offsets[t])``; the caller routes padding
    tokens to the sink page, so one compile per token budget serves
    every admission mix without corrupting live pages.
    """
    key_cache = key_cache.at[dest_blocks, dest_offsets].set(k_new)
    value_cache = value_cache.at[dest_blocks, dest_offsets].set(v_new)
    return key_cache, value_cache


# ---------------------------------------------------------------------------
# quantized (int8) write paths: quantize ON WRITE inside the compiled step
# ---------------------------------------------------------------------------
def _quant_write_tokens(cache, scale, new_vals, blks, offs, amax=None):
    """Core of every int8 write path (traceable).

    cache [phys, bs, Hkv, D] int8, scale [phys, Hkv] fp32 absmax,
    new_vals [N, Hkv, D] float, blks/offs [N] int32 (token t lands at
    ``(blks[t], offs[t])``; padding routed to the sink page by the
    caller, exactly like the fp32 paths).

    Per-page-per-head RUNNING-MAX scale: a scatter-max folds the new
    tokens' absmax into each touched page's scale (duplicate pages in
    one write accumulate correctly), then the touched pages' EXISTING
    codes are rescaled by old/new in the same dispatch (ratio 1 —
    bit-exact round trip — whenever the scale didn't move, which is the
    steady state) and the new tokens are quantized with the final
    scale.  Dequantization therefore always uses the exact scale each
    code was (re)quantized with.  Scales are monotone per page
    lifetime in the pool array; a recycled page keeps its last absmax
    as the quantization floor — bounded coarseness, zero extra
    dispatches in the hot loop (K/V magnitudes are stationary across
    requests, so the floor tracks the data).

    ``amax`` (round 17): the fused RoPE+QKV epilogue already computed
    each token's per-head absmax in its single pass over the
    projection outputs — pass it here to skip the re-read (it is
    bit-identical to what this function would recompute).
    """
    from ..quantization.functional import quantize_symmetric
    f32 = jnp.float32
    vals = new_vals.astype(f32)
    if amax is None:
        amax = jnp.max(jnp.abs(vals), axis=-1)           # [N, Hkv]
    new_scale = scale.at[blks].max(amax)                 # running max
    ratio = jnp.where(new_scale > 0,
                      scale / jnp.maximum(new_scale, 1e-30),
                      jnp.ones((), f32))
    # rescale the touched pages' existing codes (gather → scatter;
    # duplicate blks write identical content, so order is irrelevant)
    pages = cache[blks].astype(f32) * ratio[blks][:, None, :, None]
    cache = cache.at[blks].set(jnp.round(pages).astype(cache.dtype))
    q = quantize_symmetric(vals, new_scale[blks][:, :, None])
    cache = cache.at[blks, offs].set(q.astype(cache.dtype))
    return cache, new_scale


def _quant_write_one_per_page(cache, scale, new_vals, blks, offs,
                              amax=None):
    """``_quant_write_tokens`` specialized to AT MOST ONE token per
    live page (the decode append: every slot writes its own sequence's
    page; only sink duplicates, which hold garbage anyway).  The
    rescaled page and its new token row merge into ONE scatter — half
    the scatter traffic of the general path on the hottest write."""
    from ..quantization.functional import quantize_symmetric
    f32 = jnp.float32
    bs = cache.shape[1]
    vals = new_vals.astype(f32)
    if amax is None:
        amax = jnp.max(jnp.abs(vals), axis=-1)           # [N, Hkv]
    new_scale = scale.at[blks].max(amax)
    ratio = jnp.where(new_scale > 0,
                      scale / jnp.maximum(new_scale, 1e-30),
                      jnp.ones((), f32))
    pages = jnp.round(cache[blks].astype(f32)
                      * ratio[blks][:, None, :, None])
    q = quantize_symmetric(vals, new_scale[blks][:, :, None])
    row = jnp.arange(bs, dtype=jnp.int32)[None, :] == offs[:, None]
    pages = jnp.where(row[:, :, None, None], q[:, None], pages)
    return cache.at[blks].set(pages.astype(cache.dtype)), new_scale


def write_decode_kv_q8(k_new, v_new, key_cache, value_cache, key_scale,
                       value_scale, block_tables, seq_lens,
                       k_amax=None, v_amax=None):
    """int8 variant of ``write_decode_kv`` (the fused decode append):
    k_new/v_new [B, Hkv, D] quantized into position seq_lens[b]'s page
    with per-page-per-head running-max scales.  Returns
    ``(key_cache, value_cache, key_scale, value_scale)``.

    PRECONDITION (stricter than the fp variant): at most one LIVE page
    per batch row — the fast path merges each row's token into its
    whole rescaled page and scatters page-wise, so two rows addressing
    the same physical page would be last-writer-wins.  The decode
    append satisfies this by construction (every slot appends to its
    OWN sequence's tail page; only masked slots share the sink page,
    whose content is garbage either way).  For multi-token-per-page
    writes use ``write_ragged_kv_q8``/``write_chunk_kv_q8``."""
    bs = key_cache.shape[1]
    pos = seq_lens.astype(jnp.int32)
    blk = jnp.take_along_axis(block_tables, (pos // bs)[:, None],
                              axis=1)[:, 0]
    off = pos % bs
    key_cache, key_scale = _quant_write_one_per_page(
        key_cache, key_scale, k_new, blk, off, amax=k_amax)
    value_cache, value_scale = _quant_write_one_per_page(
        value_cache, value_scale, v_new, blk, off, amax=v_amax)
    return key_cache, value_cache, key_scale, value_scale


def write_chunk_kv_q8(k_new, v_new, key_cache, value_cache, key_scale,
                      value_scale, block_table_row, start, n_valid, sink,
                      k_amax=None, v_amax=None):
    """int8 variant of ``write_chunk_kv``: one bucket-padded prefill
    chunk quantized into its pages (padding → sink, whose scale is
    garbage-on-garbage, exactly like its codes)."""
    C = k_new.shape[1]
    bs = key_cache.shape[1]
    idx = jnp.arange(C, dtype=jnp.int32)
    pos = start.astype(jnp.int32) + idx
    blk = block_table_row[0, pos // bs]
    valid = idx < n_valid
    blk = jnp.where(valid, blk, jnp.int32(sink))
    off = jnp.where(valid, pos % bs, 0)
    key_cache, key_scale = _quant_write_tokens(
        key_cache, key_scale, k_new[0], blk, off, amax=k_amax)
    value_cache, value_scale = _quant_write_tokens(
        value_cache, value_scale, v_new[0], blk, off, amax=v_amax)
    return key_cache, value_cache, key_scale, value_scale


def write_ragged_kv_q8(k_new, v_new, key_cache, value_cache, key_scale,
                       value_scale, dest_blocks, dest_offsets,
                       k_amax=None, v_amax=None):
    """int8 variant of ``write_ragged_kv``: the packed ragged token
    batch (decode spans + prefill chunks) quantized in ONE scatter
    inside the fused MixedStep trace."""
    key_cache, key_scale = _quant_write_tokens(
        key_cache, key_scale, k_new, dest_blocks, dest_offsets,
        amax=k_amax)
    value_cache, value_scale = _quant_write_tokens(
        value_cache, value_scale, v_new, dest_blocks, dest_offsets,
        amax=v_amax)
    return key_cache, value_cache, key_scale, value_scale


def dequant_pages(pages, page_scale):
    """Dequantize gathered int8 pages: ``pages [..., bs, Hkv, D]`` ×
    their ``page_scale [..., Hkv]`` → fp32 (traceable; the read-side
    inverse of ``_quant_write_tokens``)."""
    from ..quantization.functional import dequantize_symmetric
    return dequantize_symmetric(pages, page_scale[..., None, :, None])


def _ragged_attention_xla(q, key_cache, value_cache, block_tables,
                          q_offsets, q_lens, kv_lens, scale,
                          key_scale=None, value_scale=None):
    """Ragged paged attention, XLA reference path (CPU + parity tests).

    q: [T, H, D] packed ragged tokens; block_tables [S, W]; q_offsets /
    q_lens / kv_lens [S] describe the spans (q_offsets ascending, with
    padding spans pinned past the last token so no token maps to them).
    Token t of span s sits at global position
    ``kv_lens[s] - q_lens[s] + (t - q_offsets[s])`` and attends keys at
    positions <= that — the same mask decode (q_len=1) and chunked
    prefill use, so one code path covers any admission mix.  Same
    gather + fp32 masked softmax pattern as ``_paged_attention_xla``.
    """
    T, H, D = q.shape
    Hkv = key_cache.shape[2]
    bs = key_cache.shape[1]
    W = block_tables.shape[1]
    max_len = W * bs
    tok = jnp.arange(T, dtype=jnp.int32)
    sid = jnp.clip(
        jnp.searchsorted(q_offsets.astype(jnp.int32), tok, side="right")
        - 1, 0, q_offsets.shape[0] - 1).astype(jnp.int32)
    qpos = (kv_lens[sid] - q_lens[sid] + (tok - q_offsets[sid]))
    qpos = jnp.maximum(qpos, 0)       # padding tokens: finite garbage
    bt = jnp.maximum(block_tables, 0)[sid]               # [T, W]
    if key_scale is not None:
        # int8 pool: dequantize the GATHERED pages (cast + one fused
        # broadcast multiply — measured fastest of the CPU variants;
        # the Pallas kernel dequantizes per DMA'd page instead)
        k = dequant_pages(key_cache[bt], key_scale[bt])
        v = dequant_pages(value_cache[bt], value_scale[bt])
    else:
        k, v = key_cache[bt], value_cache[bt]
    k = k.reshape(T, max_len, Hkv, D)
    v = v.reshape(T, max_len, Hkv, D)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("thd,tlhd->thl",
                   q.astype(jnp.float32) * jnp.float32(scale),
                   k.astype(jnp.float32))
    cols = jnp.arange(max_len, dtype=jnp.int32)
    valid = cols[None, None, :] <= qpos[:, None, None]
    s = jnp.where(valid, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("thl,tlhd->thd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# context-parallel (round 22) per-stripe partials: each chip's pool shard
# holds slot sub-range [r*bsl, (r+1)*bsl) of EVERY page (the
# P(None, cp, tp, None) dim-1 striping), so the local flattened kv index
# j maps to GLOBAL position (j // bsl)*block_size + stripe_offset +
# (j % bsl).  These variants run the same gather + fp32 masked softmax
# as their full counterparts but over the local stripe only, returning
# the NORMALIZED (o, m, l) rows the cross-chip merge
# (ops/online_softmax.merge_partials) combines exactly.  XLA-only for
# now: CPU dryruns and the parity/bench gates use these; a per-stripe
# (m, l)-emitting Pallas variant is the TPU follow-up.  int8 pools are
# rejected under cp at engine construction (per-chip absmax scales over
# a replicated [phys, Hkv] table would diverge), so no scale operands.
# ---------------------------------------------------------------------------
def _stripe_cols(n_pages, bsl, stripe_offset, global_block_size):
    """Global kv position of each local flattened stripe index."""
    j = jnp.arange(n_pages * bsl, dtype=jnp.int32)
    return ((j // bsl) * jnp.int32(global_block_size)
            + stripe_offset.astype(jnp.int32) + (j % bsl))


def _partial_softmax_rows(s, valid, v, contract):
    """Masked partial softmax over the last score axis: returns the
    normalized output plus the (m, l) merge rows; an all-masked row
    yields (o=0, m=-inf, l=0) — the exact empty-stripe identity
    ``merge_partials`` drops."""
    m = jnp.max(s, axis=-1)
    m_safe = jnp.where(jnp.isfinite(m), m, np.float32(0.0))
    p = jnp.where(valid, jnp.exp(s - m_safe[..., None]), np.float32(0.0))
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(contract, p, v)
    return o / jnp.maximum(l, np.float32(1e-30))[..., None], m, l


def _ragged_attention_xla_partial(q, key_cache, value_cache,
                                  block_tables, q_offsets, q_lens,
                                  kv_lens, scale, stripe_offset,
                                  global_block_size):
    """Per-stripe ragged attention partial (cp shard of
    ``_ragged_attention_xla``): q [T, H, D] against the LOCAL pool
    stripe [phys, bsl, Hkv, D]; returns fp32 ``(o [T,H,D], m [T,H],
    l [T,H])`` for the cross-chip merge."""
    T, H, D = q.shape
    Hkv = key_cache.shape[2]
    bsl = key_cache.shape[1]
    W = block_tables.shape[1]
    tok = jnp.arange(T, dtype=jnp.int32)
    sid = jnp.clip(
        jnp.searchsorted(q_offsets.astype(jnp.int32), tok, side="right")
        - 1, 0, q_offsets.shape[0] - 1).astype(jnp.int32)
    qpos = (kv_lens[sid] - q_lens[sid] + (tok - q_offsets[sid]))
    qpos = jnp.maximum(qpos, 0)
    bt = jnp.maximum(block_tables, 0)[sid]               # [T, W]
    k = key_cache[bt].reshape(T, W * bsl, Hkv, D)
    v = value_cache[bt].reshape(T, W * bsl, Hkv, D)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("thd,tlhd->thl",
                   q.astype(jnp.float32) * jnp.float32(scale),
                   k.astype(jnp.float32))
    gcol = _stripe_cols(W, bsl, stripe_offset, global_block_size)
    valid = gcol[None, None, :] <= qpos[:, None, None]
    s = jnp.where(valid, s, -jnp.inf)
    return _partial_softmax_rows(s, valid, v.astype(jnp.float32),
                                 "thl,tlhd->thd")


def _paged_attention_xla_partial(q, key_cache, value_cache,
                                 block_tables, seq_lens, scale,
                                 stripe_offset, global_block_size):
    """Per-stripe decode attention partial (cp shard of
    ``_paged_attention_xla``): q [B, H, D]; returns fp32
    ``(o [B,H,D], m [B,H], l [B,H])``."""
    B, H, D = q.shape
    Hkv = key_cache.shape[2]
    bsl = key_cache.shape[1]
    W = block_tables.shape[1]
    bt = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)
    k = key_cache[bt].reshape(B, W * bsl, Hkv, D)
    v = value_cache[bt].reshape(B, W * bsl, Hkv, D)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,blhd->bhl",
                   q.astype(jnp.float32) * jnp.float32(scale),
                   k.astype(jnp.float32))
    gcol = _stripe_cols(W, bsl, stripe_offset, global_block_size)
    valid = gcol[None, None, :] < seq_lens[:, None, None]
    s = jnp.where(valid, s, -jnp.inf)
    return _partial_softmax_rows(s, valid, v.astype(jnp.float32),
                                 "bhl,blhd->bhd")


def chunk_prefill_attention_partial(q, key_cache, value_cache,
                                    block_table_row, start, scale,
                                    stripe_offset, global_block_size):
    """Per-stripe chunked-prefill attention partial (cp shard of
    ``chunk_prefill_attention``): q [1, C, H, D] at global positions
    start..start+C-1; returns fp32 ``(o [1,C,H,D], m [1,C,H],
    l [1,C,H])``.  The causal ``gcol <= qpos`` mask also covers
    never-written pages (their global columns exceed every query
    position), so the r10 poison-page invariant survives the gather."""
    B, C, H, D = q.shape
    Hkv = key_cache.shape[2]
    bsl = key_cache.shape[1]
    W = int(block_table_row.shape[1])
    qf = q[0].astype(jnp.float32) * jnp.float32(scale)   # [C, H, D]
    qpos = start.astype(jnp.int32) + jnp.arange(C, dtype=jnp.int32)
    bt = jnp.maximum(block_table_row[0].astype(jnp.int32), 0)   # [W]
    k = key_cache[bt].reshape(W * bsl, Hkv, D)
    v = value_cache[bt].reshape(W * bsl, Hkv, D)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("qhd,khd->qhk", qf, k.astype(jnp.float32))
    gcol = _stripe_cols(W, bsl, stripe_offset, global_block_size)
    valid = gcol[None, None, :] <= qpos[:, None, None]
    s = jnp.where(valid, s, -jnp.inf)
    o, m, l = _partial_softmax_rows(s, valid, v.astype(jnp.float32),
                                    "qhk,khd->qhd")
    return o[None], m[None], l[None]


def ragged_paged_attention(q, key_cache, value_cache, block_tables,
                           q_offsets, q_lens, kv_lens,
                           use_pallas: Optional[bool] = None,
                           interpret=False, span_q: Optional[int] = None,
                           key_scale=None, value_scale=None,
                           pipelined: bool = True):
    """One fused attention launch over a packed ragged query batch
    against the paged KV pool (arXiv:2604.15464).

    q: [T, H, D] — decode slots contribute length-1 spans, prefill
    chunks length-C spans, concatenated on the token axis.
    block_tables: [S, W] int32 per-span page lists (-1/sink padded).
    q_offsets/q_lens/kv_lens: [S] int32 span tables (kv_len INCLUDES the
    span's own tokens, which must already be written to the pages).
    key_scale/value_scale: per-page-per-head [phys, Hkv] fp32 absmax
    tables of an int8 pool (dequantized into the fp32 online-softmax);
    None for fp pools.  Returns [T, H, D].
    """
    tensor_in = isinstance(q, Tensor)
    qv = _val(q)
    kc, vc = _val(key_cache), _val(value_cache)
    bt = jnp.asarray(np.asarray(block_tables), jnp.int32)
    qo = jnp.asarray(np.asarray(q_offsets), jnp.int32)
    ql = jnp.asarray(np.asarray(q_lens), jnp.int32)
    kl = jnp.asarray(np.asarray(kv_lens), jnp.int32)
    scale = 1.0 / math.sqrt(qv.shape[-1])
    if use_pallas is None:
        use_pallas = _HAS_PLTPU and _on_tpu()
    if use_pallas or interpret:
        from .pallas_kernels import _ragged_paged_attention_pallas
        sq = int(span_q) if span_q else int(np.max(np.asarray(q_lens)))
        out = _ragged_paged_attention_pallas(
            qv, kc, vc, bt, qo, ql, kl, scale, span_q=sq,
            interpret=interpret, key_scale=key_scale,
            value_scale=value_scale, pipelined=pipelined)
    else:
        out = _ragged_attention_xla(qv, kc, vc, bt, qo, ql, kl, scale,
                                    key_scale, value_scale)
    return Tensor._from_value(out) if tensor_in else out


def write_kv_to_cache(k_new, v_new, key_cache, value_cache, block_tables,
                      seq_lens, donate: bool = False):
    """Append K/V into page slots; returns NEW (key_cache, value_cache).

    k_new/v_new: [B, Hkv, D] (decode) or [B, S, Hkv, D] (prefill,
    written starting at seq_lens).  donate=True consumes the passed cache
    buffers (in-place HBM update — the serving loop's mode); the default
    keeps them valid for the caller."""
    k_new, v_new = _val(k_new), _val(v_new)
    key_cache, value_cache = _val(key_cache), _val(value_cache)
    block_tables = jnp.asarray(np.asarray(block_tables), jnp.int32)
    seq_lens = jnp.asarray(np.asarray(seq_lens), jnp.int32)
    if k_new.ndim == 3:
        fn = _write_decode_donated if donate else _write_decode
    else:
        fn = _write_prefill_donated if donate else _write_prefill
    return fn(k_new, v_new, key_cache, value_cache, block_tables,
              seq_lens)


def reconstruct_kv(key_cache, value_cache, block_tables, max_len,
                   key_scale=None, value_scale=None):
    """Gather pages back to dense [B, max_len, Hkv, D] (XLA path);
    int8 pools dequantize through their per-page-per-head scales."""
    bt = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)
    k = key_cache[bt]          # [B, max_blocks, bs, Hkv, D]
    v = value_cache[bt]
    if key_scale is not None:
        k = dequant_pages(k, key_scale[bt])
        v = dequant_pages(v, value_scale[bt])
    B, nb, bs, H, D = k.shape
    k = k.reshape(B, nb * bs, H, D)[:, :max_len]
    v = v.reshape(B, nb * bs, H, D)[:, :max_len]
    return k, v


# ---------------------------------------------------------------------------
# decode attention: XLA gather path (reference + CPU)
# ---------------------------------------------------------------------------
def _paged_attention_xla(q, key_cache, value_cache, block_tables, seq_lens,
                         scale, key_scale=None, value_scale=None):
    B, H, D = q.shape
    Hkv = key_cache.shape[2]
    bs = key_cache.shape[1]
    max_len = int(block_tables.shape[1]) * bs
    k, v = reconstruct_kv(key_cache, value_cache, block_tables, max_len,
                          key_scale, value_scale)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    cols = jnp.arange(s.shape[-1], dtype=jnp.int32)
    valid = cols[None, None, :] < seq_lens[:, None, None]
    s = jnp.where(valid, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,blhd->bhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# decode attention: Pallas TPU kernel
# ---------------------------------------------------------------------------
def _paged_decode_kernel(# scalar prefetch (+2 bitcast scale tables
                         # when quantized)
                         *refs,
                         block_size: int, pages_per_seq: int,
                         scale: float, groups: int,
                         quantized: bool = False,
                         pipelined: bool = True):
    """Grid cell (b, hkv): one batch row, one kv head; q carries the
    `groups` query heads mapped to this kv head.

    Pages stream HBM->VMEM through two buffers per operand (round 17,
    ``pipelined=True``): page i+1's async copy is issued before the
    attention math on page i, the wait lands at the buffer swap, and
    the prefetch is clamped to the sequence's used page count so the
    block table is never read past ``seq_len``'s coverage.
    ``pipelined=False`` keeps the r16 issue-then-wait loop for
    old-vs-new benching.  Online-softmax state stays in fp32 registers.

    An int8 pool's per-page-per-head fp32 scales ride as TWO EXTRA
    scalar-prefetch tables bitcast to int32 ([Hkv, phys] — SMEM scalar
    reads with a dynamic page index, the same mechanism as the block
    table).  Pipelined, the q heads are quantized once per cell to
    per-row int8 and ``q·Kᵀ`` runs int8×int8 on the MXU with the q/k/
    softmax scales folded into the int32-accumulated scores
    (``quantization.functional.fold_int8_scores``); the v scale folds
    into the [groups, D] ``p·V`` product.  Legacy (non-pipelined)
    dequantizes each page right after its DMA, exactly the r13 math.
    Only int8 bytes ever cross HBM→VMEM on either path."""
    from ..quantization.functional import (fold_int8_scores,
                                           quantize_rows_symmetric)
    if quantized:
        (block_tables_ref, seq_lens_ref, ks_bits_ref, vs_bits_ref,
         q_ref, k_pages_ref, v_pages_ref, o_ref,
         k_vmem, v_vmem, sem) = refs
    else:
        (block_tables_ref, seq_lens_ref,
         q_ref, k_pages_ref, v_pages_ref, o_ref,
         k_vmem, v_vmem, sem) = refs
        ks_bits_ref = vs_bits_ref = None
    b = pl.program_id(0)
    h = pl.program_id(1)
    seq_len = seq_lens_ref[b]
    int8_mxu = quantized and pipelined
    if int8_mxu:
        q_codes, q_s = quantize_rows_symmetric(q_ref[0, 0])
        g, d = q_codes.shape
        q = None
    else:
        q = q_ref[0, 0].astype(jnp.float32) * scale    # [groups, D]
        g, d = q.shape

    m0 = jnp.full((g, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    acc0 = jnp.zeros((g, d), jnp.float32)

    n_pages = jnp.minimum(
        (seq_len + jnp.int32(block_size - 1)) // jnp.int32(block_size),
        jnp.int32(pages_per_seq))

    def page_math(p_idx, page, kbuf, vbuf, carry):
        if quantized:
            sk = jax.lax.bitcast_convert_type(ks_bits_ref[h, page],
                                              jnp.float32)
            sv = jax.lax.bitcast_convert_type(vs_bits_ref[h, page],
                                              jnp.float32)
        if int8_mxu:
            si = jax.lax.dot_general(
                q_codes, kbuf, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            s = fold_int8_scores(si, q_s, sk, scale)
        else:
            k = kbuf.astype(jnp.float32)               # [bs, D]
            if quantized:
                k = k * (sk / np.float32(_KV_BNT))
            s = q @ k.T                                # [groups, bs]
        base = p_idx * jnp.int32(block_size)
        cols = base + jax.lax.broadcasted_iota(jnp.int32, (g, block_size), 1)
        ok = cols < seq_len
        s = jnp.where(ok, s, -jnp.inf)

        def pv_of_p(p):
            if int8_mxu:
                # p·V as int8×int8 as well: per-row p scales + the
                # page's v scale fold into the [groups, D] product, so
                # the page never materializes in fp32
                p_codes, p_s = quantize_rows_symmetric(p)
                pvi = jax.lax.dot_general(
                    p_codes, vbuf, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                return fold_int8_scores(pvi, p_s, sv)
            v = vbuf.astype(jnp.float32)
            if quantized:
                v = v * (sv / np.float32(_KV_BNT))
            return p @ v

        return online_softmax_update(carry, s, ok, pv_of_p)

    if pipelined:
        def start_page(p_idx, slot):
            page = block_tables_ref[b, p_idx]
            pltpu.make_async_copy(k_pages_ref.at[h, page],
                                  k_vmem.at[slot], sem.at[slot, 0]).start()
            pltpu.make_async_copy(v_pages_ref.at[h, page],
                                  v_vmem.at[slot], sem.at[slot, 1]).start()

        def wait_page(p_idx, slot):
            page = block_tables_ref[b, p_idx]
            pltpu.make_async_copy(k_pages_ref.at[h, page],
                                  k_vmem.at[slot], sem.at[slot, 0]).wait()
            pltpu.make_async_copy(v_pages_ref.at[h, page],
                                  v_vmem.at[slot], sem.at[slot, 1]).wait()

        # a masked slot (seq_len 0) has NO used page: nothing to warm
        @pl.when(n_pages > 0)
        def _warm():
            start_page(jnp.int32(0), jnp.int32(0))

        def body(p_idx, carry):
            slot = jax.lax.rem(p_idx, jnp.int32(2))
            # prefetch clamp: the last used page issues no copy, so the
            # block table is never read past the used page count
            @pl.when(p_idx + 1 < n_pages)
            def _prefetch():
                start_page(p_idx + 1, jnp.int32(1) - slot)
            wait_page(p_idx, slot)
            return page_math(p_idx, block_tables_ref[b, p_idx],
                             k_vmem[slot], v_vmem[slot], carry)
    else:
        def body(p_idx, carry):
            page = block_tables_ref[b, p_idx]
            k_copy = pltpu.make_async_copy(
                k_pages_ref.at[h, page], k_vmem, sem)
            k_copy.start()
            k_copy.wait()
            v_copy = pltpu.make_async_copy(
                v_pages_ref.at[h, page], v_vmem, sem)
            v_copy.start()
            v_copy.wait()
            return page_math(p_idx, page, k_vmem[...], v_vmem[...], carry)

    m, l, acc = jax.lax.fori_loop(jnp.int32(0), n_pages, body,
                                  (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _paged_attention_pallas(q, key_cache, value_cache, block_tables,
                            seq_lens, scale, interpret=False,
                            key_scale=None, value_scale=None,
                            pipelined: bool = True):
    B, H, D = q.shape
    Hkv = key_cache.shape[2]
    bs = key_cache.shape[1]
    groups = H // Hkv
    pages_per_seq = block_tables.shape[1]
    quantized = key_scale is not None
    # [B, H, D] -> [B, Hkv, groups, D]; pages -> [Hkv, nb, bs, D]
    qg = q.reshape(B, Hkv, groups, D)
    kp = jnp.moveaxis(key_cache, 2, 0)      # [Hkv, nb, bs, D]
    vp = jnp.moveaxis(value_cache, 2, 0)
    if not quantized:
        kp, vp = kp.astype(jnp.float32), vp.astype(jnp.float32)
    bt = jnp.maximum(block_tables, 0)

    kernel = functools.partial(
        _paged_decode_kernel, block_size=bs, pages_per_seq=pages_per_seq,
        scale=scale, groups=groups, quantized=quantized,
        pipelined=pipelined)
    if pipelined:
        page_scratch = [pltpu.VMEM((2, bs, D), kp.dtype),
                        pltpu.VMEM((2, bs, D), vp.dtype),
                        pltpu.SemaphoreType.DMA((2, 2))]
    else:
        page_scratch = [pltpu.VMEM((bs, D), kp.dtype),
                        pltpu.VMEM((bs, D), vp.dtype),
                        pltpu.SemaphoreType.DMA]

    with jax.experimental.disable_x64():
        prefetch = [bt.astype(jnp.int32), seq_lens.astype(jnp.int32)]
        if quantized:
            # fp32 scales ride the int32 scalar-prefetch lane bitcast;
            # [phys, Hkv] -> [Hkv, phys] so the kernel indexes [h, page]
            prefetch += [
                jax.lax.bitcast_convert_type(
                    key_scale.astype(jnp.float32).T, jnp.int32),
                jax.lax.bitcast_convert_type(
                    value_scale.astype(jnp.float32).T, jnp.int32)]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(prefetch),
            grid=(B, Hkv),
            in_specs=[
                pl.BlockSpec((1, 1, groups, D),
                             lambda b, h, *_: (b, h, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
                pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            ],
            out_specs=pl.BlockSpec((1, 1, groups, D),
                                   lambda b, h, *_: (b, h, 0, 0)),
            scratch_shapes=page_scratch,
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, Hkv, groups, D), q.dtype),
            interpret=interpret,
        )(*prefetch, qg, kp, vp)
    return out.reshape(B, H, D)


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def paged_attention(q, key_cache, value_cache, block_tables, seq_lens,
                    use_pallas: Optional[bool] = None, interpret=False,
                    key_scale=None, value_scale=None,
                    pipelined: bool = True):
    """Decode-step attention over a paged KV cache.

    q: [B, H, D] (one query token per sequence)
    key_cache/value_cache: [num_blocks, block_size, Hkv, D]
    block_tables: [B, max_blocks] int32, -1 padded
    seq_lens: [B] int32 — number of valid tokens ALREADY in the cache
    key_scale/value_scale: [phys, Hkv] fp32 absmax tables of an int8
    pool (None for fp pools).  Returns [B, H, D].
    """
    tensor_in = isinstance(q, Tensor)
    qv = _val(q)
    kc, vc = _val(key_cache), _val(value_cache)
    bt = jnp.asarray(np.asarray(block_tables), jnp.int32)
    sl = jnp.asarray(np.asarray(seq_lens), jnp.int32)
    scale = 1.0 / math.sqrt(qv.shape[-1])
    if use_pallas is None:
        use_pallas = _HAS_PLTPU and _on_tpu()
    if use_pallas or interpret:
        out = _paged_attention_pallas(qv, kc, vc, bt, sl, scale,
                                      interpret=interpret,
                                      key_scale=key_scale,
                                      value_scale=value_scale,
                                      pipelined=pipelined)
    else:
        out = _paged_attention_xla(qv, kc, vc, bt, sl, scale,
                                   key_scale, value_scale)
    return Tensor._from_value(out) if tensor_in else out


# ---------------------------------------------------------------------------
# fused serving ops (reference API parity)
# ---------------------------------------------------------------------------
def block_multihead_attention(qkv, key_cache, value_cache, seq_lens,
                              block_tables, num_heads: int,
                              head_dim: Optional[int] = None,
                              donate_cache: bool = False):
    """Parity: paddle.incubate.nn.functional.block_multihead_attention
    (phi/kernels/fusion/block_multihead_attention_kernel.cu), simplified to
    the two serving phases:

    - prefill (qkv [B, S, (H+2Hkv)*D], seq_lens==0): causal self-attention,
      writes K/V pages, returns [B, S, H*D]
    - decode (qkv [B, 1, ...], seq_lens>0): appends one token and runs
      paged attention, returns [B, 1, H*D]

    Returns (out, key_cache, value_cache, new_seq_lens).
    """
    qkv_v = _val(qkv)
    kc, vc = _val(key_cache), _val(value_cache)
    B, S = qkv_v.shape[:2]
    Hkv = kc.shape[2]
    D = head_dim or kc.shape[3]
    H = num_heads
    q, k, v = jnp.split(qkv_v.reshape(B, S, -1, D), [H, H + Hkv], axis=2)
    sl = jnp.asarray(np.asarray(seq_lens), jnp.int32)

    # donate_cache=True is the serving-loop fast path (in-place HBM write
    # per token) — ONLY safe when the caller rebinds to the returned
    # caches and holds no other reference to the passed buffers; the
    # default keeps the inputs valid
    kc, vc = write_kv_to_cache(k, v, kc, vc, block_tables, sl,
                               donate=donate_cache)
    new_len = sl + S

    if S > 1:
        # prefill: dense causal attention over what was just written
        from .pallas_kernels import _chunked_sdpa
        qh = jnp.moveaxis(q, 2, 1)        # [B, H, S, D]
        kh = jnp.moveaxis(k, 2, 1)
        vh = jnp.moveaxis(v, 2, 1)
        if Hkv != H:
            rep = H // Hkv
            kh = jnp.repeat(kh, rep, axis=1)
            vh = jnp.repeat(vh, rep, axis=1)
        out = _chunked_sdpa(qh, kh, vh, True)
        out = jnp.moveaxis(out, 1, 2).reshape(B, S, H * D)
    else:
        out = paged_attention(q[:, 0], kc, vc, block_tables, new_len)
        out = out.reshape(B, 1, H * D)
    if isinstance(qkv, Tensor):
        out = Tensor._from_value(jnp.asarray(out))
    return out, kc, vc, new_len


def masked_multihead_attention(x, cache_kv, seq_lens=None,
                               num_heads: Optional[int] = None):
    """Parity: masked_multihead_attention (dense-cache decode step).

    x: packed qkv [B, 3*H*D] for ONE new token.
    cache_kv: [2, B, H, max_len, D]; seq_lens [B] tokens already cached.
    Returns (out [B, H*D], updated cache_kv, new_seq_lens)."""
    xv = _val(x)
    cache = _val(cache_kv)
    B = xv.shape[0]
    H = num_heads or cache.shape[2]
    D = cache.shape[4]
    max_len = cache.shape[3]
    q, k, v = jnp.split(xv.reshape(B, 3, H, D), 3, axis=1)
    q, k, v = q[:, 0], k[:, 0], v[:, 0]
    if seq_lens is None:
        seq_lens = jnp.zeros((B,), jnp.int32)
    sl = jnp.asarray(np.asarray(seq_lens), jnp.int32)

    bidx = jnp.arange(B)
    cache = cache.at[0, bidx, :, sl].set(k)
    cache = cache.at[1, bidx, :, sl].set(v)
    new_len = sl + 1

    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bhd,bhld->bhl", q.astype(jnp.float32) * scale,
                   cache[0].astype(jnp.float32))
    cols = jnp.arange(max_len, dtype=jnp.int32)
    s = jnp.where(cols[None, None, :] < new_len[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,bhld->bhd", p,
                     cache[1].astype(jnp.float32)).astype(xv.dtype)
    out = out.reshape(B, H * D)
    if isinstance(x, Tensor):
        out = Tensor._from_value(out)
    return out, cache, new_len
