"""Paged (block) attention for serving.

Parity: the reference's LLM-serving fused kernels
(paddle/phi/kernels/fusion/block_multihead_attention_kernel.cu — paged KV
cache addressed through per-sequence block tables — and
masked_multihead_attention for dense-cache decode).

TPU-native design: the KV cache is a pool of fixed-size pages
``[num_blocks, block_size, kv_heads, head_dim]`` living in HBM; a batch
addresses it through ``block_tables [B, max_blocks]``.  Decode attention
runs as a Pallas kernel — grid over (batch, kv_head), the page list is a
scalar-prefetch operand, and pages are DMA'd HBM→VMEM with online-softmax
accumulation — so one query token never materializes the gathered
[L, D] cache in HBM.  An XLA gather fallback covers CPU and is the
numerics reference in tests.
"""
from __future__ import annotations

import functools
import math
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..core.tensor import Tensor

__all__ = ["PagedKVCache", "paged_attention", "write_kv_to_cache",
           "write_decode_kv", "write_prefill_kv", "write_chunk_kv",
           "write_ragged_kv", "chunk_prefill_attention",
           "ragged_paged_attention",
           "reconstruct_kv", "block_multihead_attention",
           "masked_multihead_attention"]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# cache pool management (host-side; the reference keeps this in the
# serving runtime around the kernel too)
# ---------------------------------------------------------------------------
class PagedKVCache:
    """A pool of KV pages plus a per-layer free-list/block-table manager.

    One instance serves one transformer layer.  Arrays are jax arrays so
    updates stay on device; the free list is host state (allocation is
    control flow, not compute).

    Pages are REFCOUNTED: ``allocate_block`` hands out a page with one
    reference, ``share_blocks`` adds references (prefix caching — two
    requests whose prompts share a prefix address the same physical
    pages), and ``free_sequence`` is the single release path: it drops
    one reference per page and only returns a page to the free list
    when its count reaches zero.  A page shared by a prefix-cache table
    or another live request's block table therefore survives any one
    holder finishing (including pool-dry victim truncation and
    lazy-alloc growth — both funnel through ``free_sequence``).
    """

    def __init__(self, num_blocks: int, block_size: int, num_kv_heads: int,
                 head_dim: int, dtype=jnp.float32, sink_block: bool = False):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        # sink_block=True adds ONE extra physical page, never in the free
        # list, exposed as .sink: a fixed-shape compiled decode step
        # routes the writes of inactive (masked) batch slots there, so
        # slot occupancy changes never corrupt live pages and never
        # change any traced shape.
        self.sink = num_blocks if sink_block else -1
        phys = num_blocks + (1 if sink_block else 0)
        shape = (phys, block_size, num_kv_heads, head_dim)
        self.key_cache = jnp.zeros(shape, dtype)
        self.value_cache = jnp.zeros(shape, dtype)
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref: dict = {}            # block id -> live reference count

    def place(self, sharding):
        """Place both pools with a ``NamedSharding`` — the
        tensor-parallel serving engine head-shards them
        (``P(None, None, 'tp', None)``): each chip physically holds
        only its kv-head slice of every page, so per-chip pool HBM is
        exactly 1/tp.  Free-list/refcount state is host bookkeeping and
        needs no placement.  Call once at engine construction, before
        any compiled step consumes (donates) the arrays."""
        self.key_cache = jax.device_put(self.key_cache, sharding)
        self.value_cache = jax.device_put(self.value_cache, sharding)

    def per_chip_pool_bytes(self) -> int:
        """Bytes of ONE chip's shard of this layer's K+V pools (the
        whole pool when unsharded) — the capacity number the
        multi-chip serving bench gates at ≈ pool/tp."""
        total = 0
        for arr in (self.key_cache, self.value_cache):
            shape = arr.sharding.shard_shape(arr.shape) \
                if getattr(arr, "sharding", None) is not None \
                else arr.shape
            total += int(np.prod(shape)) * arr.dtype.itemsize
        return total

    def allocate_block(self) -> int:
        if not self._free:
            raise RuntimeError(
                "PagedKVCache out of blocks (%d in pool); raise num_blocks "
                "or free finished sequences" % self.num_blocks)
        b = self._free.pop()
        self._ref[b] = 1
        return b

    def share_blocks(self, block_ids):
        """Add one reference to each page (prefix sharing)."""
        for b in block_ids:
            b = int(b)
            if b < 0 or b == self.sink:
                continue
            if b not in self._ref:
                raise RuntimeError(
                    "share_blocks(%d): page is not allocated" % b)
            self._ref[b] += 1

    def refcount(self, block_id: int) -> int:
        return self._ref.get(int(block_id), 0)

    def free_sequence(self, block_ids):
        """Drop one reference per page; recycle pages that hit zero.
        The ONLY release path — every finish/truncate/evict goes
        through here, so a shared page is never recycled while another
        holder's block table still references it."""
        for b in block_ids:
            b = int(b)
            if b < 0 or b == self.sink:
                continue
            n = self._ref.pop(b, 1) - 1
            if n > 0:
                self._ref[b] = n
            else:
                self._free.append(b)

    def blocks_needed(self, seq_len: int) -> int:
        return -(-seq_len // self.block_size)

    def build_block_table(self, seq_lens, max_blocks=None) -> np.ndarray:
        """Allocate pages for new sequences; returns [B, max_blocks]
        int32 table (-1 padded)."""
        tables = []
        for L in seq_lens:
            n = self.blocks_needed(max(int(L), 1))
            tables.append([self.allocate_block() for _ in range(n)])
        width = max_blocks or max(len(t) for t in tables)
        out = np.full((len(tables), width), -1, np.int32)
        for i, t in enumerate(tables):
            out[i, :len(t)] = t
        return out

    def append(self, k_new, v_new, block_tables, seq_lens):
        """Donating in-place append: updates self.key_cache/value_cache
        (the old buffers are consumed — use this, not the functional
        write_kv_to_cache, when the pool object owns the arrays)."""
        self.key_cache, self.value_cache = _write_decode_donated(
            _val(k_new), _val(v_new), self.key_cache, self.value_cache,
            jnp.asarray(np.asarray(block_tables), jnp.int32),
            jnp.asarray(np.asarray(seq_lens), jnp.int32))

    def ensure_capacity(self, block_tables: np.ndarray,
                        seq_lens) -> np.ndarray:
        """Grow tables so every sequence can hold seq_len+1 tokens."""
        bt = np.asarray(block_tables).copy()
        for i, L in enumerate(np.asarray(seq_lens)):
            need = self.blocks_needed(int(L) + 1)
            have = int((bt[i] >= 0).sum())
            while have < need:
                if (bt[i] >= 0).sum() == bt.shape[1]:
                    bt = np.concatenate(
                        [bt, np.full((bt.shape[0], 1), -1, np.int32)], 1)
                bt[i, have] = self.allocate_block()
                have += 1
        return bt


# ---------------------------------------------------------------------------
# cache write (scatter one new token per sequence)
# ---------------------------------------------------------------------------
def _write_decode_impl(k_new, v_new, key_cache, value_cache, block_tables,
                       seq_lens):
    """k_new/v_new [B, Hkv, D]; writes at position seq_lens[b]."""
    bs = key_cache.shape[1]
    pos = seq_lens.astype(jnp.int32)
    blk = jnp.take_along_axis(block_tables, (pos // bs)[:, None],
                              axis=1)[:, 0]
    off = pos % bs
    key_cache = key_cache.at[blk, off].set(k_new)
    value_cache = value_cache.at[blk, off].set(v_new)
    return key_cache, value_cache


# functional API: callers keep ownership of all buffers (no donation);
# PagedKVCache.append is the donating variant that rebinds its own state
_write_decode = jax.jit(_write_decode_impl)
_write_decode_donated = jax.jit(_write_decode_impl, donate_argnums=(2, 3))


def _write_prefill_impl(k_new, v_new, key_cache, value_cache, block_tables,
                        seq_lens):
    """k_new/v_new [B, S, Hkv, D]: one vectorized scatter for the whole
    prompt (not S sequential dispatches)."""
    B, S = k_new.shape[:2]
    bs = key_cache.shape[1]
    pos = seq_lens[:, None].astype(jnp.int32) + jnp.arange(
        S, dtype=jnp.int32)[None, :]                      # [B, S]
    blk = jnp.take_along_axis(block_tables, pos // bs, axis=1)  # [B, S]
    off = pos % bs
    key_cache = key_cache.at[blk, off].set(k_new)
    value_cache = value_cache.at[blk, off].set(v_new)
    return key_cache, value_cache


_write_prefill = jax.jit(_write_prefill_impl)
_write_prefill_donated = jax.jit(_write_prefill_impl, donate_argnums=(2, 3))

# traceable (un-jitted) functional appends: COMPOSE these under an outer
# jax.jit (the serving engine's single fused decode step) — calling the
# jitted variants from inside a trace would nest dispatches instead of
# fusing the scatter into the surrounding module
write_decode_kv = _write_decode_impl
write_prefill_kv = _write_prefill_impl


def write_chunk_kv(k_new, v_new, key_cache, value_cache, block_table_row,
                   start, n_valid, sink):
    """Scatter one PADDED prefill chunk into cache pages (traceable —
    composed inside the bucketed ``PrefillStep`` trace).

    k_new/v_new: [1, C, Hkv, D] where C is the bucket width; only the
    first ``n_valid`` positions carry real tokens.  Position i lands at
    sequence position ``start + i``; padded positions (i >= n_valid)
    are routed to the ``sink`` page so one compile per bucket serves
    every prompt length that rounds up to it without corrupting live
    pages.  start/n_valid are traced scalars: chunk offset and fill
    level never retrace.
    """
    C = k_new.shape[1]
    bs = key_cache.shape[1]
    idx = jnp.arange(C, dtype=jnp.int32)
    pos = start.astype(jnp.int32) + idx                      # [C]
    # OOB pos//bs for the padded tail clamps in the gather, then the
    # where() routes those writes to the sink page anyway
    blk = block_table_row[0, pos // bs]                      # [C]
    valid = idx < n_valid
    blk = jnp.where(valid, blk, jnp.int32(sink))
    off = jnp.where(valid, pos % bs, 0)
    key_cache = key_cache.at[blk, off].set(k_new[0])
    value_cache = value_cache.at[blk, off].set(v_new[0])
    return key_cache, value_cache


def chunk_prefill_attention(q, key_cache, value_cache, block_table_row,
                            start, scale):
    """Causal attention for one padded prefill chunk over the paged
    cache (traceable; the bucketed ``PrefillStep``'s attention body).

    q: [1, C, H, D] — chunk queries at global positions start..start+C-1
    (the chunk's own K/V must already be written to the pages).  Masks
    keys to ``kpos <= qpos``, so chunk offset stays a traced scalar: one
    compile per bucket covers every chunk position, every prompt length
    in the bucket, and every prefix-cache suffix offset.  Padded queries
    produce garbage rows the caller never reads (the sampled token comes
    from position n_valid-1).

    The page loop is CLAMPED to the chunk's used block count
    ``ceil((start + C) / block_size)`` — a traced loop bound, so a short
    sequence in a large pool pays attention FLOPs proportional to its
    own fill, not the full table width.  Numerics: the row max is exact
    over the used window (identical to the full-width masked max, since
    every clamped-away key was -inf there), then the normalizer and the
    weighted sum accumulate page by page in position order.
    """
    B, C, H, D = q.shape
    Hkv = key_cache.shape[2]
    bs = key_cache.shape[1]
    W = int(block_table_row.shape[1])
    rep = H // Hkv
    qf = q[0].astype(jnp.float32) * jnp.float32(scale)   # [C, H, D]
    qpos = start.astype(jnp.int32) + jnp.arange(C, dtype=jnp.int32)
    n_used = jnp.minimum(
        (start.astype(jnp.int32) + C + bs - 1) // bs, jnp.int32(W))
    bt = jnp.maximum(block_table_row[0].astype(jnp.int32), 0)

    def page_scores(p_idx, k):
        # k [bs, H, D] (GQA-repeated) -> scores [H, C, bs], causal-masked
        s = jnp.einsum("qhd,khd->hqk", qf, k)
        cols = p_idx * bs + jnp.arange(bs, dtype=jnp.int32)
        ok = cols[None, None, :] <= qpos[None, :, None]
        return jnp.where(ok, s, -jnp.inf)

    def gather(p_idx, cache):
        page = cache[bt[p_idx]].astype(jnp.float32)      # [bs, Hkv, D]
        if rep != 1:
            page = jnp.repeat(page, rep, axis=1)
        return page

    def max_body(p_idx, m):
        s = page_scores(p_idx, gather(p_idx, key_cache))
        return jnp.maximum(m, jnp.max(s, axis=-1))

    m = jax.lax.fori_loop(jnp.int32(0), n_used, max_body,
                          jnp.full((H, C), -jnp.inf, jnp.float32))

    def acc_body(p_idx, carry):
        l, acc = carry
        s = page_scores(p_idx, gather(p_idx, key_cache))
        p = jnp.exp(s - m[:, :, None])                   # -inf keys -> 0
        l = l + jnp.sum(p, axis=-1)
        acc = acc + jnp.einsum("hqk,khd->qhd", p,
                               gather(p_idx, value_cache))
        return l, acc

    l, acc = jax.lax.fori_loop(
        jnp.int32(0), n_used, acc_body,
        (jnp.zeros((H, C), jnp.float32),
         jnp.zeros((C, H, D), jnp.float32)))
    out = acc / jnp.maximum(l, 1e-30).T[:, :, None]
    return out[None].astype(q.dtype)


def write_ragged_kv(k_new, v_new, key_cache, value_cache, dest_blocks,
                    dest_offsets):
    """Scatter a packed ragged token batch's K/V into cache pages
    (traceable — composed inside the fused ``MixedStep`` trace).

    k_new/v_new: [T, Hkv, D] — one row per packed token (decode slots
    and prefill-chunk tokens interleaved).  Token t lands at
    ``(dest_blocks[t], dest_offsets[t])``; the caller routes padding
    tokens to the sink page, so one compile per token budget serves
    every admission mix without corrupting live pages.
    """
    key_cache = key_cache.at[dest_blocks, dest_offsets].set(k_new)
    value_cache = value_cache.at[dest_blocks, dest_offsets].set(v_new)
    return key_cache, value_cache


def _ragged_attention_xla(q, key_cache, value_cache, block_tables,
                          q_offsets, q_lens, kv_lens, scale):
    """Ragged paged attention, XLA reference path (CPU + parity tests).

    q: [T, H, D] packed ragged tokens; block_tables [S, W]; q_offsets /
    q_lens / kv_lens [S] describe the spans (q_offsets ascending, with
    padding spans pinned past the last token so no token maps to them).
    Token t of span s sits at global position
    ``kv_lens[s] - q_lens[s] + (t - q_offsets[s])`` and attends keys at
    positions <= that — the same mask decode (q_len=1) and chunked
    prefill use, so one code path covers any admission mix.  Same
    gather + fp32 masked softmax pattern as ``_paged_attention_xla``.
    """
    T, H, D = q.shape
    Hkv = key_cache.shape[2]
    bs = key_cache.shape[1]
    W = block_tables.shape[1]
    max_len = W * bs
    tok = jnp.arange(T, dtype=jnp.int32)
    sid = jnp.clip(
        jnp.searchsorted(q_offsets.astype(jnp.int32), tok, side="right")
        - 1, 0, q_offsets.shape[0] - 1).astype(jnp.int32)
    qpos = (kv_lens[sid] - q_lens[sid] + (tok - q_offsets[sid]))
    qpos = jnp.maximum(qpos, 0)       # padding tokens: finite garbage
    bt = jnp.maximum(block_tables, 0)[sid]               # [T, W]
    k = key_cache[bt].reshape(T, max_len, Hkv, D)
    v = value_cache[bt].reshape(T, max_len, Hkv, D)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("thd,tlhd->thl",
                   q.astype(jnp.float32) * jnp.float32(scale),
                   k.astype(jnp.float32))
    cols = jnp.arange(max_len, dtype=jnp.int32)
    valid = cols[None, None, :] <= qpos[:, None, None]
    s = jnp.where(valid, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("thl,tlhd->thd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ragged_paged_attention(q, key_cache, value_cache, block_tables,
                           q_offsets, q_lens, kv_lens,
                           use_pallas: Optional[bool] = None,
                           interpret=False, span_q: Optional[int] = None):
    """One fused attention launch over a packed ragged query batch
    against the paged KV pool (arXiv:2604.15464).

    q: [T, H, D] — decode slots contribute length-1 spans, prefill
    chunks length-C spans, concatenated on the token axis.
    block_tables: [S, W] int32 per-span page lists (-1/sink padded).
    q_offsets/q_lens/kv_lens: [S] int32 span tables (kv_len INCLUDES the
    span's own tokens, which must already be written to the pages).
    Returns [T, H, D].
    """
    tensor_in = isinstance(q, Tensor)
    qv = _val(q)
    kc, vc = _val(key_cache), _val(value_cache)
    bt = jnp.asarray(np.asarray(block_tables), jnp.int32)
    qo = jnp.asarray(np.asarray(q_offsets), jnp.int32)
    ql = jnp.asarray(np.asarray(q_lens), jnp.int32)
    kl = jnp.asarray(np.asarray(kv_lens), jnp.int32)
    scale = 1.0 / math.sqrt(qv.shape[-1])
    if use_pallas is None:
        use_pallas = _HAS_PLTPU and _on_tpu()
    if use_pallas or interpret:
        from .pallas_kernels import _ragged_paged_attention_pallas
        sq = int(span_q) if span_q else int(np.max(np.asarray(q_lens)))
        out = _ragged_paged_attention_pallas(
            qv, kc, vc, bt, qo, ql, kl, scale, span_q=sq,
            interpret=interpret)
    else:
        out = _ragged_attention_xla(qv, kc, vc, bt, qo, ql, kl, scale)
    return Tensor._from_value(out) if tensor_in else out


def write_kv_to_cache(k_new, v_new, key_cache, value_cache, block_tables,
                      seq_lens, donate: bool = False):
    """Append K/V into page slots; returns NEW (key_cache, value_cache).

    k_new/v_new: [B, Hkv, D] (decode) or [B, S, Hkv, D] (prefill,
    written starting at seq_lens).  donate=True consumes the passed cache
    buffers (in-place HBM update — the serving loop's mode); the default
    keeps them valid for the caller."""
    k_new, v_new = _val(k_new), _val(v_new)
    key_cache, value_cache = _val(key_cache), _val(value_cache)
    block_tables = jnp.asarray(np.asarray(block_tables), jnp.int32)
    seq_lens = jnp.asarray(np.asarray(seq_lens), jnp.int32)
    if k_new.ndim == 3:
        fn = _write_decode_donated if donate else _write_decode
    else:
        fn = _write_prefill_donated if donate else _write_prefill
    return fn(k_new, v_new, key_cache, value_cache, block_tables,
              seq_lens)


def reconstruct_kv(key_cache, value_cache, block_tables, max_len):
    """Gather pages back to dense [B, max_len, Hkv, D] (XLA path)."""
    bt = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)
    k = key_cache[bt]          # [B, max_blocks, bs, Hkv, D]
    v = value_cache[bt]
    B, nb, bs, H, D = k.shape
    k = k.reshape(B, nb * bs, H, D)[:, :max_len]
    v = v.reshape(B, nb * bs, H, D)[:, :max_len]
    return k, v


# ---------------------------------------------------------------------------
# decode attention: XLA gather path (reference + CPU)
# ---------------------------------------------------------------------------
def _paged_attention_xla(q, key_cache, value_cache, block_tables, seq_lens,
                         scale):
    B, H, D = q.shape
    Hkv = key_cache.shape[2]
    max_len = int(block_tables.shape[1]) * key_cache.shape[1]
    k, v = reconstruct_kv(key_cache, value_cache, block_tables, max_len)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    cols = jnp.arange(s.shape[-1], dtype=jnp.int32)
    valid = cols[None, None, :] < seq_lens[:, None, None]
    s = jnp.where(valid, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,blhd->bhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# decode attention: Pallas TPU kernel
# ---------------------------------------------------------------------------
def _paged_decode_kernel(# scalar prefetch
                         block_tables_ref, seq_lens_ref,
                         # operands
                         q_ref, k_pages_ref, v_pages_ref,
                         # output
                         o_ref,
                         # scratch
                         k_vmem, v_vmem, sem,
                         *, block_size: int, pages_per_seq: int,
                         scale: float, groups: int):
    """Grid cell (b, hkv): one batch row, one kv head; q carries the
    `groups` query heads mapped to this kv head.

    Pages are copied HBM->VMEM one at a time with an async DMA, with the
    online-softmax running state in fp32 registers."""
    b = pl.program_id(0)
    h = pl.program_id(1)
    seq_len = seq_lens_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale        # [groups, D]
    g, d = q.shape

    m0 = jnp.full((g, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    acc0 = jnp.zeros((g, d), jnp.float32)

    n_pages = jnp.minimum(
        (seq_len + jnp.int32(block_size - 1)) // jnp.int32(block_size),
        jnp.int32(pages_per_seq))

    def body(p_idx, carry):
        m, l, acc = carry
        page = block_tables_ref[b, p_idx]
        k_copy = pltpu.make_async_copy(
            k_pages_ref.at[h, page], k_vmem, sem)
        k_copy.start()
        k_copy.wait()
        v_copy = pltpu.make_async_copy(
            v_pages_ref.at[h, page], v_vmem, sem)
        v_copy.start()
        v_copy.wait()
        k = k_vmem[...].astype(jnp.float32)            # [bs, D]
        v = v_vmem[...].astype(jnp.float32)
        s = q @ k.T                                    # [groups, bs]
        base = p_idx * jnp.int32(block_size)
        cols = base + jax.lax.broadcasted_iota(jnp.int32, (g, block_size), 1)
        s = jnp.where(cols < seq_len, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(cols < seq_len, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + p @ v
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(jnp.int32(0), n_pages, body,
                                  (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _paged_attention_pallas(q, key_cache, value_cache, block_tables,
                            seq_lens, scale, interpret=False):
    B, H, D = q.shape
    Hkv = key_cache.shape[2]
    bs = key_cache.shape[1]
    groups = H // Hkv
    pages_per_seq = block_tables.shape[1]
    # [B, H, D] -> [B, Hkv, groups, D]; pages -> [Hkv, nb, bs, D]
    qg = q.reshape(B, Hkv, groups, D)
    kp = jnp.moveaxis(key_cache, 2, 0)      # [Hkv, nb, bs, D]
    vp = jnp.moveaxis(value_cache, 2, 0)
    bt = jnp.maximum(block_tables, 0)

    kernel = functools.partial(
        _paged_decode_kernel, block_size=bs, pages_per_seq=pages_per_seq,
        scale=scale, groups=groups)

    with jax.experimental.disable_x64():
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, Hkv),
            in_specs=[
                pl.BlockSpec((1, 1, groups, D),
                             lambda b, h, *_: (b, h, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
                pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            ],
            out_specs=pl.BlockSpec((1, 1, groups, D),
                                   lambda b, h, *_: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((bs, D), jnp.float32),
                pltpu.VMEM((bs, D), jnp.float32),
                pltpu.SemaphoreType.DMA,
            ],
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, Hkv, groups, D), q.dtype),
            interpret=interpret,
        )(bt.astype(jnp.int32), seq_lens.astype(jnp.int32),
          qg, kp.astype(jnp.float32), vp.astype(jnp.float32))
    return out.reshape(B, H, D)


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def paged_attention(q, key_cache, value_cache, block_tables, seq_lens,
                    use_pallas: Optional[bool] = None, interpret=False):
    """Decode-step attention over a paged KV cache.

    q: [B, H, D] (one query token per sequence)
    key_cache/value_cache: [num_blocks, block_size, Hkv, D]
    block_tables: [B, max_blocks] int32, -1 padded
    seq_lens: [B] int32 — number of valid tokens ALREADY in the cache
    Returns [B, H, D].
    """
    tensor_in = isinstance(q, Tensor)
    qv = _val(q)
    kc, vc = _val(key_cache), _val(value_cache)
    bt = jnp.asarray(np.asarray(block_tables), jnp.int32)
    sl = jnp.asarray(np.asarray(seq_lens), jnp.int32)
    scale = 1.0 / math.sqrt(qv.shape[-1])
    if use_pallas is None:
        use_pallas = _HAS_PLTPU and _on_tpu()
    if use_pallas or interpret:
        out = _paged_attention_pallas(qv, kc, vc, bt, sl, scale,
                                      interpret=interpret)
    else:
        out = _paged_attention_xla(qv, kc, vc, bt, sl, scale)
    return Tensor._from_value(out) if tensor_in else out


# ---------------------------------------------------------------------------
# fused serving ops (reference API parity)
# ---------------------------------------------------------------------------
def block_multihead_attention(qkv, key_cache, value_cache, seq_lens,
                              block_tables, num_heads: int,
                              head_dim: Optional[int] = None,
                              donate_cache: bool = False):
    """Parity: paddle.incubate.nn.functional.block_multihead_attention
    (phi/kernels/fusion/block_multihead_attention_kernel.cu), simplified to
    the two serving phases:

    - prefill (qkv [B, S, (H+2Hkv)*D], seq_lens==0): causal self-attention,
      writes K/V pages, returns [B, S, H*D]
    - decode (qkv [B, 1, ...], seq_lens>0): appends one token and runs
      paged attention, returns [B, 1, H*D]

    Returns (out, key_cache, value_cache, new_seq_lens).
    """
    qkv_v = _val(qkv)
    kc, vc = _val(key_cache), _val(value_cache)
    B, S = qkv_v.shape[:2]
    Hkv = kc.shape[2]
    D = head_dim or kc.shape[3]
    H = num_heads
    q, k, v = jnp.split(qkv_v.reshape(B, S, -1, D), [H, H + Hkv], axis=2)
    sl = jnp.asarray(np.asarray(seq_lens), jnp.int32)

    # donate_cache=True is the serving-loop fast path (in-place HBM write
    # per token) — ONLY safe when the caller rebinds to the returned
    # caches and holds no other reference to the passed buffers; the
    # default keeps the inputs valid
    kc, vc = write_kv_to_cache(k, v, kc, vc, block_tables, sl,
                               donate=donate_cache)
    new_len = sl + S

    if S > 1:
        # prefill: dense causal attention over what was just written
        from .pallas_kernels import _chunked_sdpa
        qh = jnp.moveaxis(q, 2, 1)        # [B, H, S, D]
        kh = jnp.moveaxis(k, 2, 1)
        vh = jnp.moveaxis(v, 2, 1)
        if Hkv != H:
            rep = H // Hkv
            kh = jnp.repeat(kh, rep, axis=1)
            vh = jnp.repeat(vh, rep, axis=1)
        out = _chunked_sdpa(qh, kh, vh, True)
        out = jnp.moveaxis(out, 1, 2).reshape(B, S, H * D)
    else:
        out = paged_attention(q[:, 0], kc, vc, block_tables, new_len)
        out = out.reshape(B, 1, H * D)
    if isinstance(qkv, Tensor):
        out = Tensor._from_value(jnp.asarray(out))
    return out, kc, vc, new_len


def masked_multihead_attention(x, cache_kv, seq_lens=None,
                               num_heads: Optional[int] = None):
    """Parity: masked_multihead_attention (dense-cache decode step).

    x: packed qkv [B, 3*H*D] for ONE new token.
    cache_kv: [2, B, H, max_len, D]; seq_lens [B] tokens already cached.
    Returns (out [B, H*D], updated cache_kv, new_seq_lens)."""
    xv = _val(x)
    cache = _val(cache_kv)
    B = xv.shape[0]
    H = num_heads or cache.shape[2]
    D = cache.shape[4]
    max_len = cache.shape[3]
    q, k, v = jnp.split(xv.reshape(B, 3, H, D), 3, axis=1)
    q, k, v = q[:, 0], k[:, 0], v[:, 0]
    if seq_lens is None:
        seq_lens = jnp.zeros((B,), jnp.int32)
    sl = jnp.asarray(np.asarray(seq_lens), jnp.int32)

    bidx = jnp.arange(B)
    cache = cache.at[0, bidx, :, sl].set(k)
    cache = cache.at[1, bidx, :, sl].set(v)
    new_len = sl + 1

    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bhd,bhld->bhl", q.astype(jnp.float32) * scale,
                   cache[0].astype(jnp.float32))
    cols = jnp.arange(max_len, dtype=jnp.int32)
    s = jnp.where(cols[None, None, :] < new_len[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,bhld->bhd", p,
                     cache[1].astype(jnp.float32)).astype(xv.dtype)
    out = out.reshape(B, H * D)
    if isinstance(x, Tensor):
        out = Tensor._from_value(out)
    return out, cache, new_len
