"""Online-softmax ``(m, l, o)`` carry math — the ONE implementation
(round 22).

The associative flash-attention update used to live as three drifting
copies: the ``page_math`` loops of ``_paged_decode_kernel``
(ops/paged_attention.py) and ``_ragged_paged_kernel``
(ops/pallas_kernels.py), and — with round 22's context-parallel
serving — a third copy would have appeared in the cross-chip stripe
merge.  All three now call here:

- :func:`online_softmax_update` — one accumulation step over a tile of
  masked scores, exactly the expression sequence both Pallas page loops
  have carried since r11/r17 (byte-parity-tested against the inlined
  originals in tests/test_serving_cp.py);
- :func:`merge_partials` — the SAME math lifted to merging already
  normalized per-stripe partials ``(m, l, o)``: because the update is
  associative, N stripes computed independently merge into the exact
  full-softmax result (up to float summation order);
- :func:`cross_chip_merge` — merge_partials across a mesh axis via one
  ``all_gather`` of the three small per-token rows (measured smaller
  than a log-step ring for the per-span row sizes serving ships:
  both move ``(cp-1)/cp`` of the rows per chip, the single gather in
  one collective launch).

Everything is fp32-in/fp32-out with np.float32 constants so the
globally-on x64 mode never stages an f64 op (the r11 lesson).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["online_softmax_update", "merge_partials", "cross_chip_merge"]


def online_softmax_update(carry, s, ok, pv_of_p):
    """One online-softmax accumulation step over a masked score tile.

    carry: ``(m [g,1], l [g,1], acc [g,d])`` fp32 running state
    (initialize ``m=-inf, l=0, acc=0``).  s: ``[g, t]`` fp32 scores with
    masked lanes already set to ``-inf``; ok: the ``[g, t]`` bool mask
    (re-applied after the exp so an all-masked row's ``exp(-inf - -inf)
    = nan`` never reaches the accumulators).  pv_of_p: callback
    computing the ``[g, d]`` ``p @ V`` product from the ``[g, t]``
    probability tile — site-specific (fp32 matmul, int8 MXU with folded
    scales, ...).  Returns the new ``(m, l, acc)``.
    """
    m, l, acc = carry
    m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
    p = jnp.where(ok, jnp.exp(s - m_new), np.float32(0.0))
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_new = acc * alpha + pv_of_p(p)
    return m_new, l_new, acc_new


def merge_partials(m, l, o, axis=0):
    """Merge normalized online-softmax partials along ``axis``.

    m/l: ``[..., N, ...]`` fp32 per-partial row max and normalizer;
    o: the same shape plus a trailing feature dim, already normalized
    by its OWN ``l`` (``o_i = acc_i / max(l_i, 1e-30)``).  An empty
    partial contributes ``m=-inf, l=0`` and drops out exactly
    (``w_i = l_i·exp(m_i - m*) = 0``); the ``isfinite`` guard keeps the
    all-empty row at 0 instead of ``exp(-inf - -inf) = nan``.  Since
    ``w_i·o_i = exp(m_i - m*)·acc_i`` whenever ``l_i > 0``, the merge
    reproduces the single-pass softmax up to float summation order.
    """
    m_star = jnp.max(m, axis=axis, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m_star), m_star, np.float32(0.0))
    w = l * jnp.exp(m - m_safe)
    denom = jnp.sum(w, axis=axis)
    num = jnp.sum(w[..., None] * o, axis=axis)
    return num / jnp.maximum(denom, np.float32(1e-30))[..., None]


def cross_chip_merge(o, m, l, axis_name):
    """Merge per-chip stripe partials across mesh axis ``axis_name``
    (inside a shard_map body): ONE ``all_gather`` of the three
    per-token rows, then :func:`merge_partials` over the gathered chip
    dim.  o: ``[T, H, D]``; m/l: ``[T, H]``; returns ``[T, H, D]``
    replicated across the axis (every member computes the identical
    merge of the identical gathered rows).
    """
    og = jax.lax.all_gather(o, axis_name)          # [cp, T, H, D]
    mg = jax.lax.all_gather(m, axis_name)          # [cp, T, H]
    lg = jax.lax.all_gather(l, axis_name)
    return merge_partials(mg, lg, og, axis=0)
