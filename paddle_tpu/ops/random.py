"""Random ops + generator state.

Parity: reference per-device Philox generator (paddle/phi/core/generator.h)
and python/paddle/tensor/random.py.  TPU-native design: JAX threefry keys.
A process-global Generator holds the current key and splits per call (eager).
Inside a trace, randomness must be functional: `trace_rng_scope` installs a
traced base key (to_static threads a fresh seed in as a step input, so each
compiled step gets new randomness without retracing — the analog of the
reference feeding a seed/offset into each curand kernel launch).

Parallel RNG (per-mesh-rank seeds, reference
python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py)
is built on fold_in over mesh coordinates in paddle_tpu.distributed.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .registry import register_op, register
from ._helpers import as_value, wrap


class Generator:
    """Splittable RNG state (reference: paddle/phi/core/generator.h).

    Key creation is lazy so that merely importing the framework does not
    initialize the JAX backend (important for launcher/controller
    processes that never touch devices)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = None
        self._lock = threading.Lock()

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(self._seed)
        return self

    def initial_seed(self):
        return self._seed

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            return wrap(self._key)

    def set_state(self, state):
        self._key = as_value(state)


_GLOBAL_GENERATOR = Generator(0)

# Per-seed counter-advanced generators for ops called with an explicit
# nonzero seed: successive calls with the same seed give different (but
# run-reproducible) draws, matching reference generator semantics instead
# of freezing every draw (ADVICE r1).
_SEEDED_COUNTERS: dict = {}


def _seeded_key(seed_val: int):
    c = _SEEDED_COUNTERS.get(seed_val, 0)
    _SEEDED_COUNTERS[seed_val] = c + 1
    return jax.random.fold_in(jax.random.PRNGKey(seed_val), c)

# Trace-scope key stack: when non-empty, random ops consume splits of the
# traced key instead of the global generator.
class _TraceRng(threading.local):
    def __init__(self):
        self.stack = []


_trace_rng = _TraceRng()


@contextlib.contextmanager
def trace_rng_scope(base_key):
    """Install a (possibly traced) base key for functional randomness."""
    state = {"key": base_key}
    _trace_rng.stack.append(state)
    try:
        yield
    finally:
        _trace_rng.stack.pop()


def default_generator() -> Generator:
    return _GLOBAL_GENERATOR


def seed(value: int) -> Generator:
    """paddle.seed parity."""
    return _GLOBAL_GENERATOR.manual_seed(value)


def get_rng_state():
    return [_GLOBAL_GENERATOR.get_state()]


def set_rng_state(state_list):
    _GLOBAL_GENERATOR.set_state(state_list[0])


def next_key():
    """Next RNG key — trace-aware."""
    if _trace_rng.stack:
        st = _trace_rng.stack[-1]
        st["key"], sub = jax.random.split(st["key"])
        return sub
    return _GLOBAL_GENERATOR.next_key()


def _float_dtype(dtype):
    return _dt.convert_dtype(dtype) if dtype is not None \
        else _dt.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in shape)


@register_op("rand", category="random")
def rand(shape, dtype=None, name=None):
    return wrap(jax.random.uniform(next_key(), _shape(shape),
                                   _float_dtype(dtype)))


@register_op("randn", category="random")
def randn(shape, dtype=None, name=None):
    return wrap(jax.random.normal(next_key(), _shape(shape),
                                  _float_dtype(dtype)))


@register_op("standard_normal", category="random")
def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


@register_op("normal", category="random")
def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_value(mean)
        s = as_value(std)
        shp = jnp.broadcast_shapes(
            m.shape if hasattr(m, "shape") else (),
            s.shape if hasattr(s, "shape") else ())
        return wrap(jax.random.normal(next_key(), shp,
                                      _dt.get_default_dtype()) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return wrap(jax.random.normal(next_key(), shp,
                                  _dt.get_default_dtype()) * std + mean)


@register_op("uniform", category="random")
def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = _seeded_key(seed) if seed != 0 else next_key()
    return wrap(jax.random.uniform(key, _shape(shape), _float_dtype(dtype),
                                   minval=min, maxval=max))


@register_op("randint", category="random")
def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return wrap(jax.random.randint(next_key(), _shape(shape), low, high,
                                   _dt.convert_dtype(dtype)))


@register_op("randint_like", category="random")
def randint_like(x, low=0, high=None, dtype=None, name=None):
    v = as_value(x)
    if high is None:
        low, high = 0, low
    d = _dt.convert_dtype(dtype) if dtype else v.dtype
    return wrap(jax.random.randint(next_key(), v.shape, low, high, d))


@register_op("randperm", category="random")
def randperm(n, dtype="int64", name=None):
    return wrap(jax.random.permutation(next_key(), n).astype(
        _dt.convert_dtype(dtype)))


@register_op("bernoulli", category="random", tensor_method=True)
def bernoulli(x, name=None):
    v = as_value(x)
    return wrap(jax.random.bernoulli(next_key(), v).astype(v.dtype))


@register_op("bernoulli_", category="random")
def bernoulli_(x, p=0.5, name=None):
    v = as_value(x)
    x._value = jax.random.bernoulli(next_key(), p, v.shape).astype(v.dtype)
    return x


@register_op("poisson", category="random", tensor_method=True)
def poisson(x, name=None):
    v = as_value(x)
    return wrap(jax.random.poisson(next_key(), v).astype(v.dtype))


@register_op("multinomial", category="random", tensor_method=True)
def multinomial(x, num_samples=1, replacement=False, name=None):
    v = as_value(x)
    p = v / jnp.sum(v, axis=-1, keepdims=True)
    if v.ndim == 1:
        out = jax.random.choice(next_key(), v.shape[0], (num_samples,),
                                replace=replacement, p=p)
    else:
        keys = jax.random.split(next_key(), v.shape[0])
        out = jnp.stack([
            jax.random.choice(k, v.shape[-1], (num_samples,),
                              replace=replacement, p=p[i])
            for i, k in enumerate(keys)])
    return wrap(out.astype(jnp.int64))


@register_op("exponential_", category="random")
def exponential_(x, lam=1.0, name=None):
    v = as_value(x)
    x._value = (jax.random.exponential(next_key(), v.shape, v.dtype) /
                lam).astype(v.dtype)
    return x


@register_op("normal_", category="random")
def normal_(x, mean=0.0, std=1.0, name=None):
    v = as_value(x)
    x._value = (jax.random.normal(next_key(), v.shape, v.dtype) * std +
                mean).astype(v.dtype)
    return x


@register_op("uniform_", category="random")
def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    v = as_value(x)
    key = _seeded_key(seed) if seed != 0 else next_key()
    x._value = jax.random.uniform(key, v.shape, v.dtype, min, max)
    return x


@register_op("rand_like", category="random")
def rand_like(x, dtype=None, name=None):
    v = as_value(x)
    d = _dt.convert_dtype(dtype) if dtype else v.dtype
    return wrap(jax.random.uniform(next_key(), v.shape, d))


@register_op("randn_like", category="random")
def randn_like(x, dtype=None, name=None):
    v = as_value(x)
    d = _dt.convert_dtype(dtype) if dtype else v.dtype
    return wrap(jax.random.normal(next_key(), v.shape, d))
