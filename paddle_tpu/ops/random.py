"""Random ops + generator state.

Parity: reference per-device Philox generator (paddle/phi/core/generator.h)
and python/paddle/tensor/random.py.  TPU-native design: JAX threefry keys.
A process-global Generator holds the current key and splits per call (eager).
Inside a trace, randomness must be functional: `trace_rng_scope` installs a
traced base key (to_static threads a fresh seed in as a step input, so each
compiled step gets new randomness without retracing — the analog of the
reference feeding a seed/offset into each curand kernel launch).

Parallel RNG (per-mesh-rank seeds, reference
python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py)
is built on fold_in over mesh coordinates in paddle_tpu.distributed.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .registry import register_op, register
from ._helpers import as_value, wrap


class Generator:
    """Splittable RNG state (reference: paddle/phi/core/generator.h).

    Key creation is lazy so that merely importing the framework does not
    initialize the JAX backend (important for launcher/controller
    processes that never touch devices)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = None
        self._lock = threading.Lock()

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(self._seed)
        return self

    def initial_seed(self):
        return self._seed

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            return wrap(self._key)

    def set_state(self, state):
        self._key = as_value(state)


_GLOBAL_GENERATOR = Generator(0)

# Per-seed counter-advanced generators for ops called with an explicit
# nonzero seed: successive calls with the same seed give different (but
# run-reproducible) draws, matching reference generator semantics instead
# of freezing every draw (ADVICE r1).
_SEEDED_COUNTERS: dict = {}


def _seeded_key(seed_val: int):
    c = _SEEDED_COUNTERS.get(seed_val, 0)
    _SEEDED_COUNTERS[seed_val] = c + 1
    from ..core.dispatch import _sot_recorder
    rec = _sot_recorder[0]
    if rec is not None:
        # counter-advanced seeded draws have no functional replay form
        rec.poison("explicit-seed random op inside traced frame")
    return jax.random.fold_in(jax.random.PRNGKey(seed_val), c)

# Trace-scope key stack: when non-empty, random ops consume splits of the
# traced key instead of the global generator.
class _TraceRng(threading.local):
    def __init__(self):
        self.stack = []


_trace_rng = _TraceRng()


@contextlib.contextmanager
def trace_rng_scope(base_key):
    """Install a (possibly traced) base key for functional randomness."""
    state = {"key": base_key}
    _trace_rng.stack.append(state)
    try:
        yield
    finally:
        _trace_rng.stack.pop()


def default_generator() -> Generator:
    return _GLOBAL_GENERATOR


def seed(value: int) -> Generator:
    """paddle.seed parity."""
    return _GLOBAL_GENERATOR.manual_seed(value)


def get_rng_state():
    return [_GLOBAL_GENERATOR.get_state()]


def set_rng_state(state_list):
    _GLOBAL_GENERATOR.set_state(state_list[0])


def next_key():
    """Next RNG key — trace-aware."""
    if _trace_rng.stack:
        st = _trace_rng.stack[-1]
        st["key"], sub = jax.random.split(st["key"])
        return sub
    sub = _GLOBAL_GENERATOR.next_key()
    from ..core.dispatch import _sot_recorder
    rec = _sot_recorder[0]
    if rec is not None:
        # jit/sot is recording: register the drawn key so the replayed
        # program substitutes a fresh fold-in instead of the baked draw
        rec.register_rng_key(sub)
    return sub


def _float_dtype(dtype):
    return _dt.convert_dtype(dtype) if dtype is not None \
        else _dt.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def _rng_apply(name, kernel, key=None):
    """Route a random draw through the apply_op choke point with the key
    as a visible positional argument.  jit/sot recording recognizes
    registered keys among statement args and substitutes fresh fold-ins at
    replay, so compiled programs re-randomize per call instead of baking
    the recorded draw."""
    if key is None:
        key = next_key()
    return apply_op(name, kernel, (key,))


@register_op("rand", category="random")
def rand(shape, dtype=None, name=None):
    shp, dt = _shape(shape), _float_dtype(dtype)
    return _rng_apply("rand", lambda k: jax.random.uniform(k, shp, dt))


@register_op("randn", category="random")
def randn(shape, dtype=None, name=None):
    shp, dt = _shape(shape), _float_dtype(dtype)
    return _rng_apply("randn", lambda k: jax.random.normal(k, shp, dt))


@register_op("standard_normal", category="random")
def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


@register_op("normal", category="random")
def normal(mean=0.0, std=1.0, shape=None, name=None):
    dt = _dt.get_default_dtype()
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_value(mean)
        s = as_value(std)
        shp = jnp.broadcast_shapes(
            m.shape if hasattr(m, "shape") else (),
            s.shape if hasattr(s, "shape") else ())
        return _rng_apply(
            "normal", lambda k: jax.random.normal(k, shp, dt) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return _rng_apply(
        "normal", lambda k: jax.random.normal(k, shp, dt) * std + mean)


@register_op("uniform", category="random")
def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = _seeded_key(seed) if seed != 0 else None
    shp, dt = _shape(shape), _float_dtype(dtype)
    return _rng_apply(
        "uniform",
        lambda k: jax.random.uniform(k, shp, dt, minval=min, maxval=max),
        key=key)


@register_op("randint", category="random")
def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    shp, dt = _shape(shape), _dt.convert_dtype(dtype)
    return _rng_apply(
        "randint", lambda k: jax.random.randint(k, shp, low, high, dt))


@register_op("randint_like", category="random")
def randint_like(x, low=0, high=None, dtype=None, name=None):
    v = as_value(x)
    if high is None:
        low, high = 0, low
    d = _dt.convert_dtype(dtype) if dtype else v.dtype
    shp = v.shape
    return _rng_apply(
        "randint_like", lambda k: jax.random.randint(k, shp, low, high, d))


@register_op("randperm", category="random")
def randperm(n, dtype="int64", name=None):
    d = _dt.convert_dtype(dtype)
    return _rng_apply(
        "randperm", lambda k: jax.random.permutation(k, n).astype(d))


@register_op("bernoulli", category="random", tensor_method=True)
def bernoulli(x, name=None):
    v = as_value(x)
    return _rng_apply(
        "bernoulli", lambda k: jax.random.bernoulli(k, v).astype(v.dtype))


@register_op("bernoulli_", category="random")
def bernoulli_(x, p=0.5, name=None):
    v = as_value(x)
    x._value = _rng_apply(
        "bernoulli_",
        lambda k: jax.random.bernoulli(k, p, v.shape).astype(v.dtype))._value
    return x


@register_op("poisson", category="random", tensor_method=True)
def poisson(x, name=None):
    v = as_value(x)
    return _rng_apply(
        "poisson", lambda k: jax.random.poisson(k, v).astype(v.dtype))


@register_op("multinomial", category="random", tensor_method=True)
def multinomial(x, num_samples=1, replacement=False, name=None):
    v = as_value(x)
    p = v / jnp.sum(v, axis=-1, keepdims=True)

    def kernel(k):
        if v.ndim == 1:
            return jax.random.choice(k, v.shape[0], (num_samples,),
                                     replace=replacement, p=p)
        keys = jax.random.split(k, v.shape[0])
        return jnp.stack([
            jax.random.choice(ki, v.shape[-1], (num_samples,),
                              replace=replacement, p=p[i])
            for i, ki in enumerate(keys)])

    return _rng_apply(
        "multinomial", lambda k: kernel(k).astype(jnp.int64))


@register_op("exponential_", category="random", tensor_method=True)
def exponential_(x, lam=1.0, name=None):
    v = as_value(x)
    x._value = _rng_apply(
        "exponential_",
        lambda k: (jax.random.exponential(k, v.shape, v.dtype) /
                   lam).astype(v.dtype))._value
    return x


@register_op("normal_", category="random", tensor_method=True)
def normal_(x, mean=0.0, std=1.0, name=None):
    v = as_value(x)
    x._value = _rng_apply(
        "normal_",
        lambda k: (jax.random.normal(k, v.shape, v.dtype) * std +
                   mean).astype(v.dtype))._value
    return x


@register_op("uniform_", category="random", tensor_method=True)
def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    v = as_value(x)
    key = _seeded_key(seed) if seed != 0 else None
    x._value = _rng_apply(
        "uniform_",
        lambda k: jax.random.uniform(k, v.shape, v.dtype, min, max),
        key=key)._value
    return x


@register_op("rand_like", category="random")
def rand_like(x, dtype=None, name=None):
    v = as_value(x)
    d = _dt.convert_dtype(dtype) if dtype else v.dtype
    return _rng_apply(
        "rand_like", lambda k: jax.random.uniform(k, v.shape, d))


@register_op("randn_like", category="random")
def randn_like(x, dtype=None, name=None):
    v = as_value(x)
    d = _dt.convert_dtype(dtype) if dtype else v.dtype
    return _rng_apply(
        "randn_like", lambda k: jax.random.normal(k, v.shape, d))
