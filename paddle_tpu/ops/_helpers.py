"""Shared helpers for op definitions."""
from __future__ import annotations

import functools
import sys
from typing import Any, Callable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .registry import register


def unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def as_value(x):
    """To a jax value with paddle scalar defaults."""
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return x
    arr = np.asarray(x)
    if arr.dtype == np.float64:
        arr = arr.astype(_dt.get_default_dtype())
    return jnp.asarray(arr)


def wrap(v) -> Tensor:
    return Tensor._from_value(v)


def targ(x):
    """Normalize an apply_op operand: keep Tensors (so autograd sees the
    edge), convert scalars/lists to jax values with paddle dtype defaults."""
    return x if isinstance(x, Tensor) else as_value(x)


def axis_tuple(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (int, np.integer)):
        axis = [int(axis)]
    return tuple(int(a) % ndim if ndim else int(a) for a in axis)


def def_unary(name: str, jfn: Callable, category="math", method=True,
              inplace=True, doc: str = ""):
    """Define a paddle-style unary elementwise op."""

    def op(x, name=None):
        return apply_op(op.__op_name__, jfn, (x,))

    op.__op_name__ = name
    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = doc or f"Elementwise {name} (XLA-lowered)."
    register(name, op, category=category, tensor_method=method,
             inplace_alias=inplace)
    return op


def def_binary(name: str, jfn: Callable, category="math", method=True,
               inplace=True, doc: str = ""):
    """Define a paddle-style binary (broadcasting) op."""

    def op(x, y, name=None):
        return apply_op(op.__op_name__, jfn, (x, targ(y)))

    op.__op_name__ = name
    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = doc or f"Elementwise {name} with numpy broadcasting."
    register(name, op, category=category, tensor_method=method,
             inplace_alias=inplace)
    return op


def sliding_windows(v, axis: int, size: int, step: int):
    """Gather sliding windows along ``axis``: result has the window count
    at ``axis`` and a new ``size`` dim right after it.  Shared by
    Tensor.unfold and signal.frame."""
    ax = axis % v.ndim
    n = (v.shape[ax] - size) // step + 1
    idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
    out = jnp.take(v, idx.reshape(-1), axis=ax)
    return out.reshape(v.shape[:ax] + (n, size) + v.shape[ax + 1:])


def export(module_name: str, names_fns):
    """Inject generated ops into a module namespace."""
    mod = sys.modules[module_name]
    for n, f in names_fns.items():
        setattr(mod, n, f)
