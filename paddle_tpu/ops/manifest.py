"""Single-source-of-truth op manifest (ops.yaml).

Capability parity with the reference's YAML op registry
(paddle/phi/api/yaml/ops.yaml 291 + legacy_ops.yaml 120 + op_compat.yaml):
one declarative file lists every op with its python signature; codegen in
the reference renders C++ APIs from it, here the live registry IS the
implementation and the manifest is the contract — `validate_manifest`
diffs the two in both directions (declared-but-missing = a removed op
breaks the API; registered-but-undeclared = an op shipped without being
inventoried) plus signature drift, and the test suite gates on an empty
diff.  Regenerate after adding ops:

    python -m paddle_tpu.ops.manifest regen
"""
from __future__ import annotations

import inspect
import os
from typing import Any, Dict, List, Optional

MANIFEST_PATH = os.path.join(os.path.dirname(__file__), "ops.yaml")


def _signature_entry(fn) -> List[Dict[str, Any]]:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return []
    args = []
    for p in sig.parameters.values():
        if p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
            args.append({"name": ("*" if p.kind == p.VAR_POSITIONAL
                                  else "**") + p.name})
        elif p.default is inspect.Parameter.empty:
            args.append({"name": p.name})
        else:
            args.append({"name": p.name, "default": repr(p.default)})
    return args


def build_manifest() -> List[Dict[str, Any]]:
    """Introspect the live registry into manifest entries."""
    from .registry import registered_ops
    entries = []
    for name, opdef in sorted(registered_ops().items()):
        entries.append({
            "op": name,
            "category": opdef.category,
            "tensor_method": bool(opdef.tensor_method),
            "args": _signature_entry(opdef.fn),
        })
    return entries


def write_manifest(path: str = MANIFEST_PATH):
    import yaml
    entries = build_manifest()
    header = (
        "# Op manifest — single source of truth for the op surface\n"
        "# (capability parity: paddle/phi/api/yaml/ops.yaml).\n"
        "# Regenerate: python -m paddle_tpu.ops.manifest regen\n"
        f"# ops: {len(entries)}\n")
    with open(path, "w") as f:
        f.write(header)
        yaml.safe_dump(entries, f, sort_keys=False, width=100)
    return len(entries)


def load_manifest(path: str = MANIFEST_PATH) -> List[Dict[str, Any]]:
    import yaml
    with open(path) as f:
        return yaml.safe_load(f)


def validate_manifest(path: str = MANIFEST_PATH) -> List[str]:
    """Return a list of human-readable contract violations (empty = ok)."""
    from .registry import registered_ops
    problems = []
    declared = {e["op"]: e for e in load_manifest(path)}
    live = registered_ops()

    for name in declared:
        if name not in live:
            problems.append(f"declared op '{name}' is not registered "
                            "(API removal?)")
    for name in live:
        if name not in declared:
            problems.append(f"registered op '{name}' missing from "
                            "ops.yaml (run regen)")
    for name, entry in declared.items():
        opdef = live.get(name)
        if opdef is None:
            continue
        current = _signature_entry(opdef.fn)
        if current != entry.get("args", []):
            problems.append(f"op '{name}' signature drifted: manifest "
                            f"{entry.get('args')} vs live {current}")
        if bool(entry.get("tensor_method")) != bool(opdef.tensor_method):
            problems.append(f"op '{name}' tensor_method flag drifted")
    return problems


if __name__ == "__main__":
    import sys
    if len(sys.argv) > 1 and sys.argv[1] == "regen":
        n = write_manifest()
        print(f"wrote {MANIFEST_PATH} with {n} ops")
    else:
        probs = validate_manifest()
        for p in probs:
            print("PROBLEM:", p)
        sys.exit(1 if probs else 0)
