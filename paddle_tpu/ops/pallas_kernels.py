"""Hand-written Pallas TPU kernels for the fused-op set.

Parity: the reference's fused CUDA kernel library
(paddle/phi/kernels/fusion/ — flash attention #18, fused_rms_norm #17).
These are the only hand-written kernels in the framework; everything else
is XLA.  Each kernel has an XLA fallback (the callers catch exceptions), so
CPU tests exercise the same API.

Design notes (see /opt/skills/guides/pallas_guide.md):
- flash attention: one (batch*heads, q_block) grid cell holds a q tile in
  VMEM and streams k/v tiles, keeping the running max/denominator in fp32
  (online softmax).  Causal masking skips fully-masked k tiles.
- rms_norm: row-tiled, stats in fp32.
- flash backward: FlashAttention-2 two-kernel scheme in Pallas (dq over q
  tiles, dk/dv over k tiles, p recomputed from the saved lse); masked or
  ragged configs fall back to the chunked XLA backward.
"""
from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU backend only
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ._helpers import targ


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *out_rest, block_k: int,
                      causal: bool, scale: float, q_offset_blocks: int,
                      causal_off: int = 0):
    """One grid cell: q tile [block_q, d] vs all k/v tiles.

    Online softmax with fp32 running (max, denom, acc)."""
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
    bq = q.shape[0]
    d = q.shape[1]
    kv_len = k_ref.shape[1]
    n_kb = kv_len // block_k
    qi = pl.program_id(1)

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    # all index arithmetic pinned to int32: under jax_enable_x64 python
    # ints become int64, which mosaic cannot lower (RecursionError)
    q_start = (qi + jnp.int32(q_offset_blocks)) * jnp.int32(bq)

    def body(kb, carry):
        m, l, acc = carry
        k_off = kb * jnp.int32(block_k)
        k = k_ref[0, pl.dslice(k_off, block_k)].astype(jnp.float32)
        v = v_ref[0, pl.dslice(k_off, block_k)].astype(jnp.float32)
        s = q @ k.T                                    # [bq, bk]
        if causal:
            # bottom-right aligned: row r sees cols <= r + (Sk - Sq)
            rows = q_start + jnp.int32(causal_off) + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + p @ v
        return m_new, l_new, acc_new

    if causal:
        # skip k blocks strictly after this q tile
        last_kb = jnp.minimum(
            (q_start + jnp.int32(bq - 1) + jnp.int32(causal_off))
            // jnp.int32(block_k) + jnp.int32(1), jnp.int32(n_kb))
    else:
        last_kb = jnp.int32(n_kb)
    m, l, acc = jax.lax.fori_loop(jnp.int32(0), last_kb, body,
                                  (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0] = out.astype(o_ref.dtype)
    if out_rest:
        # log-sum-exp residual for the flash backward, broadcast over a
        # 128-lane last dim to satisfy mosaic tiling (same layout as the
        # in-tree pallas flash kernel's l/m residuals); -inf for rows
        # that attended nothing (fully masked)
        lse_ref = out_rest[0]
        lse = m + jnp.log(jnp.maximum(l, 1e-30))       # [bq, 1]
        lse_ref[0] = jnp.broadcast_to(lse, (lse.shape[0], 128)).astype(
            jnp.float32)


_INTERPRET = [False]  # set True in CPU tests to run kernels interpreted


def _flash_attention_value(q, k, v, causal: bool, block_q=256, block_k=256,
                           with_lse: bool = False):
    """q,k,v: [B, H, S, D] -> [B, H, S, D]
    (+ optional lse [B*H, Sq] when with_lse — kernel-internal layout)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    if Sq % block_q or Sk % block_k:
        raise ValueError("flash kernel needs seq divisible by block size")
    scale = 1.0 / math.sqrt(D)

    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale,
                               q_offset_blocks=0, causal_off=Sk - Sq)
    out_specs = [pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype)]
    if with_lse:
        out_specs.append(pl.BlockSpec((1, block_q, 128),
                                      lambda b, i: (b, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B * H, Sq, 128),
                                              jnp.float32))
    # Kernel body traced with x64 off: mosaic cannot legalize the i64
    # scalars that python-int arithmetic produces under jax_enable_x64.
    with jax.enable_x64(False):
        res = pl.pallas_call(
            kernel,
            grid=(B * H, Sq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=_INTERPRET[0],
        )(qr, kr, vr)
    out = res[0].reshape(B, H, Sq, D)
    if with_lse:
        # compact residual [BH, Sq]: the lane broadcast is re-expanded
        # transiently in the backward (keeping it would cost 128x the
        # memory across every layer's saved residuals)
        return out, res[1][..., 0]
    return out


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         scale: float, causal_off: int):
    """dQ for one q tile: loop k/v blocks, accumulate ds @ k.

    FlashAttention-2 backward, q-parallel half: p recomputed from the
    saved lse, delta = rowsum(dO*O) precomputed host-side in XLA."""
    q = q_ref[0].astype(jnp.float32)                   # [bq, d]
    do = do_ref[0].astype(jnp.float32)                 # [bq, d]
    lse = lse_ref[0][:, 0:1].astype(jnp.float32)       # [bq, 1] (lane bcast)
    delta = delta_ref[0][:, 0:1].astype(jnp.float32)   # [bq, 1]
    bq, d = q.shape
    kv_len = k_ref.shape[1]
    n_kb = kv_len // block_k
    qi = pl.program_id(1)
    q_start = qi * jnp.int32(bq)

    def body(kb, dq):
        k_off = kb * jnp.int32(block_k)
        k = k_ref[0, pl.dslice(k_off, block_k)].astype(jnp.float32)
        v = v_ref[0, pl.dslice(k_off, block_k)].astype(jnp.float32)
        s = (q @ k.T) * scale                          # [bq, bk]
        if causal:
            rows = q_start + jnp.int32(causal_off) + \
                jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, -jnp.inf)
        # fully-masked rows have lse = -inf; exp(-inf - -inf) would be
        # NaN — their probabilities (and grads) are exactly zero
        p = jnp.where(jnp.isfinite(lse), jnp.exp(s - lse), 0.0)
        dp = do @ v.T                                  # [bq, bk]
        ds = p * (dp - delta)
        return dq + (ds @ k) * scale

    if causal:
        last_kb = jnp.minimum(
            (q_start + jnp.int32(bq - 1) + jnp.int32(causal_off))
            // jnp.int32(block_k) + jnp.int32(1), jnp.int32(n_kb))
    else:
        last_kb = jnp.int32(n_kb)
    dq0 = jnp.zeros((bq, d), jnp.float32)
    dq = jax.lax.fori_loop(jnp.int32(0), last_kb, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          scale: float, causal_off: int):
    """dK/dV for one k/v tile: loop q blocks, accumulate ds^T q / p^T dO."""
    k = k_ref[0].astype(jnp.float32)                   # [bk, d]
    v = v_ref[0].astype(jnp.float32)                   # [bk, d]
    bk, d = k.shape
    q_len = q_ref.shape[1]
    n_qb = q_len // block_q
    ki = pl.program_id(1)
    k_start = ki * jnp.int32(bk)

    def body(qb, carry):
        dk, dv = carry
        q_off = qb * jnp.int32(block_q)
        q = q_ref[0, pl.dslice(q_off, block_q)].astype(jnp.float32)
        do = do_ref[0, pl.dslice(q_off, block_q)].astype(jnp.float32)
        lse = lse_ref[0, pl.dslice(q_off, block_q), 0:1].astype(
            jnp.float32)
        delta = delta_ref[0, pl.dslice(q_off, block_q), 0:1].astype(
            jnp.float32)
        s = (q @ k.T) * scale                          # [bq_blk, bk]
        if causal:
            rows = q_off + jnp.int32(causal_off) + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(rows >= cols, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(lse), jnp.exp(s - lse), 0.0)
        dv_new = dv + p.T @ do                         # [bk, d]
        dp = do @ v.T                                  # [bq_blk, bk]
        ds = p * (dp - delta)
        dk_new = dk + (ds.T @ q) * scale
        return dk_new, dv_new

    if causal:
        # q rows attending this k tile start at k_start - causal_off
        first_qb = jnp.maximum(
            (k_start - jnp.int32(causal_off)) // jnp.int32(block_q),
            jnp.int32(0))
    else:
        first_qb = jnp.int32(0)
    zeros = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_qb, jnp.int32(n_qb), body,
                               (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_attention_bwd(q, k, v, out, lse, g, causal: bool,
                         block_q=256, block_k=256):
    """Pallas flash backward (FlashAttention-2 two-kernel scheme):
    dq parallel over q tiles; dk/dv parallel over k tiles; both recompute
    p from the forward's lse, so memory stays O(S·D + S)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    scale = 1.0 / math.sqrt(D)
    causal_off = Sk - Sq

    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)
    dor = g.reshape(B * H, Sq, D)
    # lane-broadcast lse/delta to the mosaic-tileable [BH, Sq, 128]
    # layout (transient per-layer; residual stays compact [BH, Sq])
    lser = jnp.broadcast_to(lse.reshape(B * H, Sq)[..., None],
                            (B * H, Sq, 128))
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(B * H, Sq)
    delta = jnp.broadcast_to(delta[..., None], (B * H, Sq, 128))

    full_q = pl.BlockSpec((1, Sq, D), lambda b, i: (b, 0, 0))
    full_k = pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0))
    full_row = pl.BlockSpec((1, Sq, 128), lambda b, i: (b, 0, 0))
    tile_q = pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0))
    tile_k = pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0))
    tile_row = pl.BlockSpec((1, block_q, 128), lambda b, i: (b, i, 0))

    with jax.enable_x64(False):
        dq = pl.pallas_call(
            functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                              causal=causal, scale=scale,
                              causal_off=causal_off),
            grid=(B * H, Sq // block_q),
            in_specs=[tile_q, full_k, full_k, tile_q, tile_row, tile_row],
            out_specs=tile_q,
            out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            interpret=_INTERPRET[0],
        )(qr, kr, vr, dor, lser, delta)

        dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                              causal=causal, scale=scale,
                              causal_off=causal_off),
            grid=(B * H, Sk // block_k),
            in_specs=[full_q, tile_k, tile_k, full_q, full_row, full_row],
            out_specs=[tile_k, tile_k],
            out_shape=[jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
                       jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype)],
            interpret=_INTERPRET[0],
        )(qr, kr, vr, dor, lser, delta)

    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


def _sdpa_reference(q, k, v, causal):
    """Full-materialization XLA reference (tests / tiny shapes only)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _chunked_sdpa(q, k, v, causal, mask=None, block_k=256):
    """Memory-bounded attention: lax.scan over k/v blocks with online
    softmax; each block body is rematerialized (jax.checkpoint), so the
    BACKWARD also runs block-by-block — activation memory stays
    O(S·D + S) instead of the O(S²) of the naive formulation.  Handles
    additive/bool masks and seq lengths not divisible by the block.

    Layout [B, H, S, D].  This is both the flash VJP path and the
    fallback forward for masked/ragged configs.
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bk = min(block_k, Sk)
    pad = (-Sk) % bk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    n_kb = (Sk + pad) // bk
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32) * scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (Sq, bk), 0)
    off = jax.lax.broadcasted_iota(jnp.int32, (Sq, bk), 1)
    # bottom-right-aligned causal for Sq != Sk (decode), like _sdpa_reference
    causal_off = Sk - Sq

    if mask is not None:
        if mask.dtype != jnp.bool_:
            mask = mask.astype(jnp.float32)
        if pad:
            # pad the key axis so block slices never clamp; the padded
            # columns are killed by the `cols < Sk` validity test anyway
            widths = [(0, 0)] * (mask.ndim - 1) + [(0, pad)]
            mask = jnp.pad(mask, widths)

    def block(carry, kb):
        m_, l_, acc = carry
        ks = lax.dynamic_slice_in_dim(k, kb * bk, bk, 2)
        vs = lax.dynamic_slice_in_dim(v, kb * bk, bk, 2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, ks.astype(jnp.float32))
        cols = kb * bk + off
        valid = cols < Sk
        if causal:
            valid = valid & (rows + causal_off >= cols)
        if mask is not None:
            mb = lax.dynamic_slice_in_dim(mask, kb * bk,
                                          bk, mask.ndim - 1)
            if mb.dtype == jnp.bool_:
                valid = valid & mb
            else:
                s = s + mb
        s = jnp.where(valid, s, -jnp.inf)
        m_new = jnp.maximum(m_, jnp.max(s, -1))
        # guard fully-masked rows (m_new = -inf)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m_), m_ - m_safe, -jnp.inf))
        alpha = jnp.where(jnp.isfinite(m_), alpha, 0.0)
        l_new = l_ * alpha + jnp.sum(p, -1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vs.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    # derive the carries from qf so they inherit its device-varying
    # status under shard_map (a literal zeros init would mismatch the
    # scan body's output vma when run inside ulysses/ring wrappers)
    zero_rows = qf[..., 0] * 0.0                      # [B, H, Sq] f32
    init = (zero_rows - jnp.inf,
            zero_rows,
            qf * 0.0)
    (m_, l_, acc), _ = lax.scan(jax.checkpoint(block), init,
                                jnp.arange(n_kb, dtype=jnp.int32))
    out = acc / jnp.maximum(l_, 1e-30)[..., None]
    return out.astype(q.dtype)


def _pallas_ok(q, k, mask, block=256) -> bool:
    return (_HAS_PLTPU and _on_tpu() and mask is None
            and q.shape[2] % min(block, q.shape[2]) == 0
            and k.shape[2] % min(block, k.shape[2]) == 0)


def _select_flash_blocks(q, k, v, causal):
    """(block_q, block_k) via the autotune cache (parity: the reference's
    kernel-autotune algo pick, paddle/phi/kernels/autotune/auto_tune_base.h).
    Inside a trace only the cached winner is consulted; with concrete
    buffers a miss triggers the timed search."""
    from ..incubate.autotune import (autotune_enabled, autotune_lookup,
                                     autotune_select,
                                     flash_attention_candidates)
    Sq, Sk = q.shape[2], k.shape[2]
    default = (min(256, Sq), min(256, Sk))
    if not autotune_enabled():
        return default
    sig = (tuple(q.shape), tuple(k.shape), str(q.dtype), bool(causal))
    if isinstance(q, jax.core.Tracer):
        return autotune_lookup("flash_attention", sig) or default
    return autotune_select(
        "flash_attention", sig,
        flash_attention_candidates(Sq, Sk),
        lambda cand: (lambda: _flash_attention_value(
            q, k, v, causal, cand[0], cand[1])),
        default)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_sdpa(q, k, v, causal):
    if _pallas_ok(q, k, None):
        bq, bk = _select_flash_blocks(q, k, v, causal)
        return _flash_attention_value(q, k, v, causal, bq, bk)
    return _chunked_sdpa(q, k, v, causal)


def _flash_sdpa_fwd(q, k, v, causal):
    if _pallas_ok(q, k, None):
        bq, bk = _select_flash_blocks(q, k, v, causal)
        out, lse = _flash_attention_value(q, k, v, causal, bq, bk,
                                          with_lse=True)
        return out, (q, k, v, out, lse)
    return _chunked_sdpa(q, k, v, causal), (q, k, v, None, None)


def _flash_sdpa_bwd(causal, res, g):
    q, k, v, out, lse = res
    if lse is not None:
        # Pallas flash backward: p recomputed from lse per tile, memory
        # stays O(S·D + S) and both halves run tiled on the MXU
        return _flash_attention_bwd(q, k, v, out, lse, g, causal)
    # chunked backward: block recompute keeps memory bounded (fallback
    # for masked/ragged configs the Pallas kernel rejects)
    _, vjp = jax.vjp(lambda q_, k_, v_: _chunked_sdpa(q_, k_, v_, causal),
                     q, k, v)
    return vjp(g)


_flash_sdpa.defvjp(_flash_sdpa_fwd, _flash_sdpa_bwd)


def flash_attention_tpu(query, key, value, attn_mask=None, is_causal=False):
    """Flash attention, paddle layout [B, S, H, D].

    Clean configs (no mask, block-divisible) hit the Pallas forward and
    the Pallas FlashAttention-2 backward on TPU; masked or ragged-length
    configs run the chunked online-softmax path with its block-recomputed
    backward — still memory-bounded, still one dispatched op."""

    def fn(q, k, v, *m):
        q_ = jnp.swapaxes(q, 1, 2)
        k_ = jnp.swapaxes(k, 1, 2)
        v_ = jnp.swapaxes(v, 1, 2)
        if m:
            out = _chunked_sdpa(q_, k_, v_, is_causal, mask=m[0])
        else:
            out = _flash_sdpa(q_, k_, v_, is_causal)
        return jnp.swapaxes(out, 1, 2)

    args = (query, targ(key), targ(value))
    if attn_mask is not None:
        args = args + (targ(attn_mask),)
    return apply_op("flash_attention_pallas", fn, args)


# ---------------------------------------------------------------------------
# rms_norm
# ---------------------------------------------------------------------------
def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(ms + eps) *
                w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def rms_norm_tpu(x, weight, eps=1e-6, block_rows=512):
    """Row-tiled Pallas RMSNorm (used by the bench path on TPU)."""
    if not (_HAS_PLTPU and _on_tpu()):
        raise RuntimeError("requires TPU")

    def fn(xv, wv):
        shape = xv.shape
        d = shape[-1]
        rows = int(np.prod(shape[:-1]))
        xr = xv.reshape(rows, d)
        br = min(block_rows, rows)
        if rows % br:
            br = rows
        with jax.enable_x64(False):
            out = pl.pallas_call(
                functools.partial(_rms_kernel, eps=eps),
                grid=(rows // br,),
                in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0)),
                          pl.BlockSpec((d,), lambda i: (0,))],
                out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((rows, d), xv.dtype),
            )(xr, wv)
        return out.reshape(shape)

    return apply_op("rms_norm_pallas", fn, (x, targ(weight)))


# ---------------------------------------------------------------------------
# ring attention (sequence/context parallelism over the mesh)
# ---------------------------------------------------------------------------
def ring_attention(q, k, v, axis_name: str, is_causal=False):
    """Ring attention over a mesh axis (long-context path; SURVEY.md §5.7
    notes the reference LACKS this — sep relied on model-side sharding).

    Must run inside shard_map with the sequence dim sharded over
    ``axis_name``: each step computes a local flash block then rotates k/v
    one neighbor around the ring with collective-permute (rides ICI).
    Inputs [B, S_local, H, D] (values, not Tensors)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B,H,S,D]
    scale = 1.0 / math.sqrt(q.shape[-1])
    B, H, S, D = qh.shape

    # carries are device-varying under shard_map vma checking
    def vary(x):
        return jax.lax.pcast(x, (axis_name,), to="varying")

    m = vary(jnp.full((B, H, S, 1), -jnp.inf, jnp.float32))
    l = vary(jnp.zeros((B, H, S, 1), jnp.float32))
    acc = vary(jnp.zeros((B, H, S, D), jnp.float32))

    kv = (jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2))

    def step(i, carry):
        m, l, acc, (kc, vc) = carry
        src = (idx - i) % n  # which shard's k/v we now hold
        s = jnp.einsum("bhqd,bhkd->bhqk", qh,
                       kc.astype(jnp.float32)) * scale
        if is_causal:
            rows = idx * S + jax.lax.broadcasted_iota(
                jnp.int32, (S, S), 0)
            cols = src * S + jax.lax.broadcasted_iota(
                jnp.int32, (S, S), 1)
            s = jnp.where((rows >= cols)[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        kc2 = jax.lax.ppermute(kc, axis_name, perm)
        vc2 = jax.lax.ppermute(vc, axis_name, perm)
        return m_new, l_new, acc_new, (kc2, vc2)

    m, l, acc, _ = jax.lax.fori_loop(0, n, step, (m, l, acc, kv))
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def sdpa_ring(query, key, value, mesh, axis_name: str = "sep",
              is_causal: bool = False):
    """Sequence-parallel attention over a mesh axis (SURVEY.md §5.7 —
    the beat-the-reference long-context path; the reference's snapshot
    has NO ring attention).

    q/k/v: [B, S, H, D] with S sharded over ``axis_name``.  Each rank
    computes flash blocks against its local k/v then rotates k/v around
    the ring with collective-permute (ICI); differentiable (the rotation
    loop has a static trip count, so jax.grad reverses it)."""
    from jax.sharding import PartitionSpec as P
    from ..distributed.process_mesh import as_jax_mesh

    jmesh = as_jax_mesh(mesh)
    spec = P(None, axis_name)

    def fn(q, k, v):
        ring = jax.shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, axis_name,
                                              is_causal),
            mesh=jmesh, axis_names={axis_name},
            in_specs=(spec, spec, spec), out_specs=spec)
        return ring(q, k, v)

    return apply_op("ring_attention", fn,
                    (query, targ(key), targ(value)))


def ulysses_attention(q, k, v, axis_name: str, is_causal=False):
    """DeepSpeed-Ulysses attention over a mesh axis (SURVEY.md §5.7 —
    the all-to-all long-context modality; absent from the reference
    snapshot like ring attention).

    Must run inside shard_map with the sequence dim sharded over
    ``axis_name``: an all-to-all trades the sequence shard for a HEAD
    shard (each rank then holds the FULL sequence for H/n heads), local
    full attention runs unsharded, and a second all-to-all restores the
    sequence sharding.  Two all-to-alls ride ICI; compute is exactly the
    dense/flash kernel, so Ulysses wins over ring when heads ≥ ranks and
    the per-rank full sequence fits.  Inputs [B, S_local, H, D]."""
    n = jax.lax.axis_size(axis_name)
    B, S, H, D = q.shape
    if H % n:
        raise ValueError(f"ulysses needs heads ({H}) divisible by the "
                         f"axis size ({n})")

    def seq_to_heads(x):
        # [B, S_loc, H, D] -> all_to_all -> [B, S_full, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qf = seq_to_heads(q)
    kf = seq_to_heads(k)
    vf = seq_to_heads(v)
    # local attention over the full sequence: [B, H/n, S_full, D]
    out = _chunked_sdpa(jnp.swapaxes(qf, 1, 2), jnp.swapaxes(kf, 1, 2),
                        jnp.swapaxes(vf, 1, 2), is_causal)
    out = jnp.swapaxes(out, 1, 2).astype(q.dtype)
    return heads_to_seq(out)


def sdpa_ulysses(query, key, value, mesh, axis_name: str = "sep",
                 is_causal: bool = False):
    """Sequence-parallel attention via Ulysses all-to-all (the companion
    to sdpa_ring; pick ring for S >> heads, ulysses when heads divide
    evenly and all-to-all bandwidth beats n-step rotation).

    q/k/v: [B, S, H, D] with S sharded over ``axis_name``."""
    from jax.sharding import PartitionSpec as P
    from ..distributed.process_mesh import as_jax_mesh

    jmesh = as_jax_mesh(mesh)
    spec = P(None, axis_name)

    def fn(q, k, v):
        uly = jax.shard_map(
            lambda q_, k_, v_: ulysses_attention(q_, k_, v_, axis_name,
                                                 is_causal),
            mesh=jmesh, axis_names={axis_name},
            in_specs=(spec, spec, spec), out_specs=spec)
        return uly(q, k, v)

    return apply_op("ulysses_attention", fn,
                    (query, targ(key), targ(value)))
